"""Pipeline parallelism — GPipe-style microbatching over a 'pipe' mesh axis.

Beyond-reference extension (SURVEY.md §2: PP absent in the reference).

Two execution paths:

- **Compiled** (the TPU path): when the net contains a periodic run of
  identical-structure layers (the transformer/MLP-block case every real
  pipeline targets), the ENTIRE schedule compiles to one XLA program —
  ``shard_map`` over a 1-D 'pipe' mesh, block params stacked [S, ...] and
  sharded stage-per-device, ``lax.scan`` over M + S - 1 ticks with
  ``lax.ppermute`` moving activations to the next stage each tick.  While
  microbatch m sits in stage s, microbatch m+1 computes in stage s-1 —
  the GPipe fill/drain diagram as dataflow inside the compiler, not as a
  Python loop: one compilation per config, no host-held pullbacks, and
  gradients flow through the ppermute chain via AD (its transpose is the
  reverse rotation).  Non-periodic head/tail layers run replicated, with
  their contributions masked to stage 0 / stage S-1 and grads psum'd.

- **Compiled heterogeneous** (round 4; params sharded round 5): NON-periodic
  stacks (the conv-then-dense case) also compile to one XLA program.  Under
  SPMD every device must run the same program, so the per-stage functions
  live in a ``lax.switch`` on ``lax.axis_index('pipe')``, and inter-stage
  activations — whose shapes differ between boundaries — travel as a flat
  buffer padded to the largest boundary, reshaped by each stage's branch.
  Params get the same flat-buffer treatment: each stage's tree is raveled
  into one f32 row, rows padded and stacked [S, Pmax] SHARDED over the pipe
  axis (optimizer state too), so per-device memory is ~1/S of the model —
  branch s unflattens its own row inside the switch, grads arrive on the
  owning device via the ppermute-transpose chain (no grad psum), and the
  elementwise updater acts on the rows directly (bitwise-identical to
  per-layer updates; guarded: no per-layer lr overrides / grad norm — with
  those set, params fall back to REPLICATED with a one-time stderr note).

- **Orchestrated** (explicit opt-in / fallback): per-stage ``jax.vjp``
  calls with real per-device param placement — partitions param memory for
  any net, at interpreter dispatch cost.  Supports both schedules:
  ``schedule='gpipe'`` (all forwards, then all backwards — M in-flight
  pullbacks) and ``schedule='1f1b'`` (backward of microbatch m follows its
  forward after the S-1 fill, PipeDream-flush style — at most S in-flight
  pullbacks, the activation-memory win; the bubble fraction is the same
  (S-1)/(M+S-1) as GPipe for non-interleaved stages).

Activation memory on the compiled paths: pass ``remat=True`` to
``jax.checkpoint`` each schedule tick — in one compiled program reverse-mode
AD stashes every tick's residuals regardless of schedule order (so a
compiled "1F1B" would buy nothing over GPipe); rematerializing the tick
body is the XLA-native equivalent of 1F1B's fewer-live-pullbacks win,
trading ~1 extra forward for O(1) residuals per tick.

Scope (all paths): sequential stateless nets (no BatchNorm running
stats, no masks, no TBPTT, no dropout).  Compose with DP/TP via those
masters; this one owns the pipe axis.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.backend.compat import pcast, shard_map

from deeplearning4j_tpu.models.common import notify_listeners
from deeplearning4j_tpu.observability import (
    PhaseTimers, WorkerTelemetry, instrument, step_guard,
)
from deeplearning4j_tpu.observability import shardstats
from deeplearning4j_tpu.optimize import updaters as upd
from deeplearning4j_tpu.parallel.training_master import TrainingMaster


def split_stages(net, n_stages: int) -> List[List[int]]:
    """Partition layer indices into n_stages contiguous groups minimizing
    the LARGEST stage's parameter count — the optimal contiguous partition
    (linear-partition DP, O(n² · S); n = layer count, trivially small).
    The max stage bounds both the pipeline's compute bottleneck tick and,
    on the sharded hetero path, per-device memory (Pmax), so min-max is
    the right objective (a greedy target-filling pass used to leave ~1.5x
    imbalance on mildly skewed stacks).  The reference has no analog;
    think layer-to-executor assignment."""
    counts = []
    for layer in net.layers:
        lp = net.params.get(layer.name, {})
        counts.append(sum(int(np.prod(a.shape)) for a in lp.values()) or 1)
    n = len(counts)
    n_stages = max(1, min(n_stages, n))
    prefix = np.concatenate([[0], np.cumsum(counts)])

    def seg(i, j):  # weight of layers[i:j]
        return prefix[j] - prefix[i]

    # best[k][j] = minimal max-stage weight splitting layers[:j] into k
    # stages; cut[k][j] = the last cut position achieving it
    INF = float(prefix[-1]) + 1.0
    best = [[INF] * (n + 1) for _ in range(n_stages + 1)]
    cut = [[0] * (n + 1) for _ in range(n_stages + 1)]
    best[0][0] = 0.0
    for k in range(1, n_stages + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                cost = max(best[k - 1][i], float(seg(i, j)))
                if cost < best[k][j]:
                    best[k][j] = cost
                    cut[k][j] = i
    bounds = [n]
    for k in range(n_stages, 0, -1):
        bounds.append(cut[k][bounds[-1]])
    bounds.reverse()
    return [list(range(bounds[k], bounds[k + 1]))
            for k in range(n_stages)]


def _layer_sig(layer) -> str:
    """Structural signature: full layer config minus identity — two layers
    with equal signatures are interchangeable pipeline-stage material."""
    d = layer.to_dict()
    d.pop("name", None)
    return json.dumps(d, sort_keys=True)


def find_periodic_run(sigs: List[str], n_stages: int) -> Optional[Tuple[int, int, int]]:
    """Longest run ``layers[start : start + period * blocks]`` whose signature
    sequence repeats with ``period``, with ``blocks`` a positive multiple of
    ``n_stages``.  Returns (start, period, blocks) or None."""
    n = len(sigs)
    best = None
    for period in range(1, n // 2 + 1):
        for start in range(0, n - 2 * period + 1):
            blocks = 1
            while (start + (blocks + 1) * period <= n and
                   sigs[start + blocks * period : start + (blocks + 1) * period]
                   == sigs[start : start + period]):
                blocks += 1
            blocks -= blocks % n_stages
            if blocks >= n_stages and blocks >= 2:
                size = blocks * period
                if best is None or size > best[1] * best[2]:
                    best = (start, period, blocks)
    return best


def measure_bubble_fraction(make_net, make_batch, n_stages: int,
                            mb_size: int, m_small: int = 2,
                            m_large: int = 8, iters: int = 5,
                            devices: Optional[Sequence] = None,
                            mode: str = "auto") -> Dict[str, float]:
    """Measured pipeline bubble on a real mesh (the analytic counterpart is
    ``PipelineParallelTrainingMaster.bubble_fraction``).

    Holds the microbatch SIZE fixed and times steady-state steps at two
    microbatch COUNTS: t(M) ≈ (M + S - 1)·tick + c, so the slope between
    the two isolates the per-tick cost and ``(t - M·tick) / t`` is the
    fraction of the step not doing useful microbatch work (fill/drain
    bubble + fixed overhead c — updater, reg, dispatch; both are honest
    non-useful time).  ``make_net() -> net``, ``make_batch(n) -> DataSet``.
    """
    import time as _time

    def run(M):
        net = make_net()
        master = PipelineParallelTrainingMaster(
            n_stages=n_stages, n_microbatches=M, devices=devices, mode=mode)
        ds = make_batch(M * mb_size)
        master.execute_training(net, [ds])      # build + compile
        float(net.score_value)                  # block
        t0 = _time.perf_counter()
        master.execute_training(net, [ds] * iters)
        float(net.score_value)
        return (_time.perf_counter() - t0) / iters, master

    t_small, _ = run(m_small)
    t_large, master = run(m_large)
    tick = (t_large - t_small) / (m_large - m_small)
    measured = (t_large - m_large * tick) / t_large if t_large > 0 else 0.0
    return {
        "n_stages": n_stages,
        "mode": master._mode,
        "m_small": m_small, "m_large": m_large,
        "t_small_ms": round(t_small * 1e3, 3),
        "t_large_ms": round(t_large * 1e3, 3),
        "tick_ms": round(tick * 1e3, 3),
        "bubble_measured": round(measured, 4),
        "bubble_analytic": round(master.bubble_fraction(), 4),
    }


class PipelineParallelTrainingMaster(TrainingMaster):
    def __init__(self, n_stages: Optional[int] = None,
                 n_microbatches: int = 4,
                 devices: Optional[Sequence] = None,
                 schedule: str = "gpipe",
                 mode: str = "auto",
                 remat: bool = False,
                 checkpoint_manager=None,
                 retry_policy=None):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"schedule={schedule!r}: use 'gpipe' or '1f1b'")
        if mode not in ("auto", "compiled", "orchestrated"):
            raise ValueError(
                f"mode={mode!r}: use 'auto', 'compiled' or 'orchestrated'")
        if remat and mode == "orchestrated":
            raise ValueError(
                "remat applies only to the compiled schedules (it "
                "jax.checkpoint's the compiled tick); the orchestrated "
                "path holds per-microbatch pullbacks instead — use "
                "schedule='1f1b' there for the activation-memory win")
        self.devices = list(devices if devices is not None else jax.devices())
        self.n_stages = n_stages or len(self.devices)
        if self.n_stages > len(self.devices):
            raise ValueError(
                f"{self.n_stages} stages > {len(self.devices)} devices")
        self.n_microbatches = n_microbatches
        self.schedule = schedule
        self.mode = mode
        # remat: jax.checkpoint each schedule tick in the COMPILED paths —
        # the XLA-native counterpart of 1F1B's activation-memory win.  In
        # one compiled program reverse-mode AD stashes every tick's
        # residuals (all M + S - 1 of them) regardless of schedule order,
        # so reordering backwards 1F1B-style buys nothing; what shrinks
        # live memory is rematerializing the tick body on the backward
        # pass, trading ~1 extra forward for O(1) residuals per tick.
        self.remat = remat
        self._built = False
        # registry-backed phase timers: whole-step dispatch on the compiled
        # paths; per-stage forward/backward dispatch on the orchestrated one
        self._phases = PhaseTimers("pipeline_master")
        # orchestrated path: per-STAGE step time published as
        # dl4j_worker_step_seconds{component="pipeline_master",
        # worker="stage<s>"} — stage imbalance is the pipeline's straggler
        # (the max stage bounds the bottleneck tick).  The compiled paths
        # run all stages inside one XLA program, so there is no per-stage
        # host timing to publish there.
        self._workers: Optional[WorkerTelemetry] = None
        # resilience wiring (docs/resilience.md): auto-resume on entry,
        # step-boundary saves (stage params folded back into the facade
        # only when a save is due), clean preemption stop, transient retry
        self.checkpoint_manager = checkpoint_manager
        self.retry_policy = retry_policy

    def _warn_fast_path_downgrade(self, reasons) -> None:
        """One-shot (per master) warning + flight event when the updater
        config knocks this net off the sharded fast path: param placement
        degrades from stage-per-device to fully replicated, so per-device
        memory silently holds the WHOLE model."""
        if getattr(self, "_downgrade_warned", False):
            return
        self._downgrade_warned = True
        import warnings

        from deeplearning4j_tpu.observability import get_flight_recorder

        why = "; ".join(reasons)
        warnings.warn(
            f"pipeline master: sharded param fast path DISABLED by {why} — "
            "params are replicated on every stage device (full-model "
            "memory per device).  Use mode='orchestrated' for partitioned "
            "placement, or drop the non-elementwise updater options "
            "(docs/PARALLELISM.md).", RuntimeWarning, stacklevel=3)
        get_flight_recorder().record(
            "pipeline_fast_path_downgrade", component="pipeline_master",
            reasons=reasons, n_stages=self.n_stages, mode=self.mode)

    def training_stats(self) -> Dict[str, Any]:
        """Phase-timed stats: whole-step ``dispatch`` on the compiled paths,
        ``stage{s}_fwd``/``stage{s}_bwd`` dispatch on the orchestrated one
        (same schema as the other masters; also in the registry as
        ``dl4j_phase_seconds{component="pipeline_master"}``)."""
        out = self._phases.as_dict()
        if self._workers is not None:
            out["cluster"] = self._workers.cluster_view()
        return out

    def bubble_fraction(self) -> float:
        """Analytic pipeline bubble: of the M + S - 1 schedule ticks, S - 1
        are fill/drain — identical for GPipe and non-interleaved 1F1B (1F1B
        buys activation MEMORY, not bubble).  Measured counterpart:
        ``measure_bubble_fraction``."""
        s = self.n_stages
        return (s - 1) / (self.n_microbatches + s - 1)

    # ------------------------------------------------------------ validation
    def _validate(self, net):
        if net.conf.backprop_type == "truncated_bptt":
            raise ValueError("pipeline master does not support TBPTT")
        if not hasattr(net.layers[-1], "score"):
            # every path (compiled, hetero, orchestrated) computes the loss
            # through the tail layer's score(); fail here with guidance
            # instead of deep inside a stage function
            raise ValueError(
                f"pipeline master needs the net to end in an output layer "
                f"with a score() (OutputLayer/RnnOutputLayer); got "
                f"'{net.layers[-1].name}' ({type(net.layers[-1]).__name__})")
        for layer in net.layers:
            if layer.init_state():
                raise ValueError(
                    f"pipeline master needs stateless layers; '{layer.name}' "
                    f"({type(layer).__name__}) carries state")
            if layer.dropout > 0:
                raise ValueError("pipeline master does not support dropout")

    # ------------------------------------------------------------- stage fns
    def _build(self, net):
        self._validate(net)
        self._mode = "orchestrated"
        cfg = net.conf.updater
        lr_overrides = {l.name: l.learning_rate for l in net.layers
                        if l.learning_rate is not None}
        if self.mode == "compiled" and self.n_stages < 2:
            raise ValueError("mode='compiled' needs n_stages >= 2 "
                             f"(got {self.n_stages})")
        if self.mode != "orchestrated" and self.n_stages > 1:
            # param sharding (periodic stacked OR hetero flat rows) is only
            # exact when the updater math is purely per-element: no
            # per-layer lr overrides, no per-layer grad-norm reductions
            elementwise_updater = (
                not lr_overrides
                and cfg.gradient_normalization in (None, "none"))
            if not elementwise_updater:
                # make the downgrade LOUD: these configs silently fell off
                # the sharded fast path onto replicated params (full model
                # per device) with nothing in logs or flight data naming
                # the cause (docs/PARALLELISM.md "Sharded fast path")
                self._warn_fast_path_downgrade(
                    ([f"gradient_normalization="
                      f"{cfg.gradient_normalization!r}"]
                     if cfg.gradient_normalization not in (None, "none")
                     else [])
                    + (["per-layer learning-rate overrides: "
                        + ", ".join(sorted(lr_overrides))]
                       if lr_overrides else []))
            # best path: periodic run -> stacked params SHARDED stage-per-
            # device (param memory partitioned)
            if elementwise_updater:
                run = find_periodic_run([_layer_sig(l) for l in net.layers],
                                        self.n_stages)
                if (run is not None
                        and run[0] + run[1] * run[2] < len(net.layers)):
                    self._build_compiled(net, run)
                    self._built = True
                    return
            # heterogeneous stacks still compile (switch-per-stage, padded
            # activation buffer — module docstring).  Params SHARD over the
            # pipe axis (flat-concat-pad rows, one per stage) under the
            # same elementwise guard; otherwise they stay replicated,
            # which is a per-device MEMORY cost worth flagging once.
            shard_params = elementwise_updater
            if not shard_params and self.mode == "auto":
                import sys as _sys
                print(
                    "pipeline note: auto mode compiled this non-periodic "
                    "net with REPLICATED params (per-layer lr overrides / "
                    "gradient normalization prevent the sharded flat "
                    "layout); per-device memory holds the full model — use "
                    "mode='orchestrated' for partitioned placement",
                    file=_sys.stderr)
            self._build_compiled_hetero(net, shard_params=shard_params)
            self._built = True
            return
        if self.remat:  # reachable only via n_stages == 1 (auto/compiled)
            import sys as _sys
            print("pipeline note: remat=True has no effect on the "
                  "orchestrated path (single-stage resolution); it applies "
                  "to the compiled schedules only", file=_sys.stderr)
        self.stages = split_stages(net, self.n_stages)
        self.stage_layers = [[net.layers[i] for i in s] for s in self.stages]
        out_layer = net.layers[-1]
        pre = net.conf.preprocessors

        def make_stage_fwd(idxs, layers):
            def fwd(stage_params, a):
                for gi, layer in zip(idxs, layers):
                    if gi in pre:
                        a = pre[gi](a)
                    a, _ = layer.apply(
                        stage_params[layer.name] if layer.has_params() else {},
                        {}, a, train=True, rng=None)
                return a
            return fwd

        def make_last_stage(idxs, layers):
            body = list(zip(idxs[:-1], layers[:-1]))

            def fwd_loss(stage_params, a, y):
                for gi, layer in body:
                    if gi in pre:
                        a = pre[gi](a)
                    p = stage_params.get(layer.name, {})
                    a, _ = layer.apply(p, {}, a, train=True, rng=None)
                if idxs[-1] in pre:
                    a = pre[idxs[-1]](a)
                return out_layer.score(stage_params[out_layer.name], a, y)
            return fwd_loss

        self._stage_fwds = [jax.jit(make_stage_fwd(idxs, ls))
                            for idxs, ls in zip(self.stages[:-1],
                                                self.stage_layers[:-1])]
        self._last_stage = jax.jit(make_last_stage(self.stages[-1],
                                                   self.stage_layers[-1]))
        self._reg_fns = [
            jax.jit(jax.value_and_grad(lambda sp, ls=ls: sum(
                layer.reg_score(sp.get(layer.name, {})) for layer in ls)))
            for ls in self.stage_layers
        ]
        cfg = net.conf.updater
        self._lr_overrides = {
            l.name: l.learning_rate for l in net.layers
            if l.learning_rate is not None
        }
        self._upd_cfg = cfg
        self._built = True

    def _stage_params(self, net, s: int) -> Dict[str, Any]:
        names = [net.layers[i].name for i in self.stages[s]]
        return {n: net.params[n] for n in names if n in net.params}

    # ------------------------------------------------------ compiled schedule
    def _build_compiled(self, net, run):
        """One-XLA-program GPipe: see module docstring.  Layers split as
        prefix | S stages x (blocks/S x period layers) | suffix; block params
        stack to [S, ...] leaves sharded over the 'pipe' mesh axis."""
        start, period, blocks = run
        S = self.n_stages
        per_stage = (blocks // S) * period
        seg = list(net.layers[start : start + blocks * period])
        self._pfx = list(net.layers[:start])
        self._sfx = list(net.layers[start + blocks * period:])
        self._stage_groups = [seg[s * per_stage : (s + 1) * per_stage]
                              for s in range(S)]
        self._template = self._stage_groups[0]
        from deeplearning4j_tpu.nn.layers.dense import OutputLayer as _Out

        if not self._sfx or not isinstance(self._sfx[-1], _Out):
            raise ValueError("pipeline suffix must end in an OutputLayer")
        self._mesh = Mesh(np.asarray(self.devices[:S]), ("pipe",))
        self._blk_sharding = NamedSharding(self._mesh, P("pipe"))
        self._repl_sharding = NamedSharding(self._mesh, P())
        self._upd_cfg = net.conf.updater
        self._mode = "compiled"
        self._compiled_kind = "periodic"
        self._compiled_steps = {}  # (xs.shape, ys.shape) -> jitted step

    # ------------------------------------- compiled heterogeneous schedule
    def _build_compiled_hetero(self, net, shard_params: bool = False):
        """One-XLA-program GPipe for NON-periodic stacks: stage bodies in a
        ``lax.switch`` on the pipe index, boundary activations in a flat
        padded buffer.  With ``shard_params`` (the default whenever the
        updater is exactly elementwise), each stage's param tree is raveled
        and concatenated into one f32 row, rows padded to the largest stage
        and stacked [S, Pmax] SHARDED over the pipe axis — per-device param
        (and optimizer-state) memory is ~1/S of the model, the same
        partitioning the periodic path gets from stacking, applied to
        heterogeneous trees via the flat buffer trick the activations
        already use.  Otherwise params stay replicated (see module
        docstring)."""
        self.stages = split_stages(net, self.n_stages)
        self.stage_layers = [[net.layers[i] for i in s] for s in self.stages]
        S = len(self.stages)
        self.n_stages = S
        self._mesh = Mesh(np.asarray(self.devices[:S]), ("pipe",))
        self._repl_sharding = NamedSharding(self._mesh, P())
        self._row_sharding = NamedSharding(self._mesh, P("pipe"))
        self._upd_cfg = net.conf.updater
        self._lr_overrides = {l.name: l.learning_rate for l in net.layers
                              if l.learning_rate is not None}
        self._mode = "compiled"
        self._compiled_kind = "hetero"
        self._hetero_sharded = shard_params
        if shard_params:
            self._flat_specs, self._flat_pmax = self._hetero_flat_spec(net)
        self._compiled_steps = {}

    def _hetero_flat_spec(self, net):
        """Per-stage flatten layout: (layer, path, shape, dtype, offset,
        size) per leaf, in deterministic (layer order, sorted path) order;
        returns (specs, Pmax)."""
        def leaves(d, prefix=()):
            out = []
            for k in sorted(d):
                v = d[k]
                if isinstance(v, dict):
                    out.extend(leaves(v, prefix + (k,)))
                else:
                    out.append((prefix + (k,), v))
            return out

        specs, sizes = [], []
        for ls in self.stage_layers:
            spec, off = [], 0
            for l in ls:
                for path, a in leaves(net.params.get(l.name, {}) or {}):
                    n = int(np.prod(a.shape))
                    spec.append((l.name, path, tuple(a.shape),
                                 jnp.dtype(a.dtype), off, n))
                    off += n
            specs.append(spec)
            sizes.append(off)
        return specs, max(max(sizes), 1)

    def _hetero_flatten(self, per_layer, missing_ok: bool = False):
        """Per-layer tree -> [S, Pmax] f32 rows (host side).  With
        ``missing_ok`` absent leaves flatten to zeros (fresh optimizer
        state)."""
        rows = np.zeros((len(self._flat_specs), self._flat_pmax), np.float32)
        for s, spec in enumerate(self._flat_specs):
            for lname, path, shape, dtype, off, n in spec:
                node = per_layer.get(lname, {})
                for k in path:
                    node = node.get(k, {}) if isinstance(node, dict) else {}
                if isinstance(node, dict):
                    if not missing_ok:
                        raise KeyError(f"missing param {lname}/{path}")
                    continue
                rows[s, off:off + n] = np.asarray(
                    node, np.float32).reshape(-1)
        return jnp.asarray(rows)

    def _hetero_unflatten_host(self, rows) -> Dict[str, Any]:
        """[S, Pmax] rows -> per-layer tree (host side, original dtypes)."""
        rows = np.asarray(rows)
        out: Dict[str, Any] = {}
        for s, spec in enumerate(self._flat_specs):
            for lname, path, shape, dtype, off, n in spec:
                node = out.setdefault(lname, {})
                for k in path[:-1]:
                    node = node.setdefault(k, {})
                node[path[-1]] = jnp.asarray(
                    rows[s, off:off + n].reshape(shape).astype(dtype))
        return out

    def _hetero_stage_tree(self, s: int, flat):
        """Unflatten ONE stage's tree from its local flat row (traced)."""
        out: Dict[str, Any] = {}
        for lname, path, shape, dtype, off, n in self._flat_specs[s]:
            node = out.setdefault(lname, {})
            for k in path[:-1]:
                node = node.setdefault(k, {})
            node[path[-1]] = flat[off:off + n].reshape(shape).astype(dtype)
        return out

    def _make_hetero_step(self, net, x_mb_shape, x_dtype):
        S = len(self.stage_layers)
        M = self.n_microbatches
        cfg = self._upd_cfg
        stage_layers = self.stage_layers
        stage_idxs = self.stages
        out_layer = stage_layers[-1][-1]
        pre = net.conf.preprocessors

        def stage_fwd(s, tree, a):
            n = len(stage_layers[s]) - (1 if s == S - 1 else 0)
            for j in range(n):
                gi = stage_idxs[s][j]
                if gi in pre:
                    a = pre[gi](a)
                a, _ = stage_layers[s][j].apply(
                    tree.get(stage_layers[s][j].name, {}), {}, a,
                    train=True, rng=None)
            if s == S - 1 and stage_idxs[s][-1] in pre:
                a = pre[stage_idxs[s][-1]](a)   # preprocessor feeding the head
            return a

        # boundary shapes: output of stage s == input of stage s + 1
        bound = []
        probe = jax.ShapeDtypeStruct(x_mb_shape, x_dtype)
        for s in range(S - 1):
            probe = jax.eval_shape(
                lambda tr, a, s=s: stage_fwd(s, tr, a), net.params, probe)
            bound.append(probe)
        buf_dtype = jnp.result_type(*[b.dtype for b in bound])
        buf = max(int(np.prod(b.shape)) for b in bound)

        def schedule_loss(tree_for, xs, ys, idx):
            """The GPipe tick scan for ONE device's stage(s).  ``tree_for(s)``
            is called INSIDE branch s — with sharded params it unflattens
            the device's own row there, so only the taken branch's stage
            tree ever materializes (lax.switch executes one branch); the
            ppermute stays OUTSIDE the switch (collectives must sit at a
            uniform program point across devices)."""
            perm = [(i, i + 1) for i in range(S - 1)]

            def make_branch(s):
                def br(state, t):
                    tree = tree_for(s)
                    if s == 0:
                        a = xs[jnp.clip(t, 0, M - 1)]
                    else:
                        b = bound[s - 1]
                        n = int(np.prod(b.shape))
                        a = state[:n].reshape(b.shape).astype(b.dtype)
                    a = stage_fwd(s, tree, a)
                    if s == S - 1:
                        m_out = t - (S - 1)
                        l = out_layer.score(
                            tree.get(out_layer.name, {}), a,
                            ys[jnp.clip(m_out, 0, M - 1)])
                        return (jnp.zeros((buf,), buf_dtype),
                                l.astype(jnp.float32))
                    flat = a.reshape(-1).astype(buf_dtype)
                    return (jnp.pad(flat, (0, buf - flat.shape[0])),
                            jnp.zeros((), jnp.float32))
                return br

            branches = [make_branch(s) for s in range(S)]
            state0 = pcast(jnp.zeros((buf,), buf_dtype), ("pipe",),
                               to="varying")
            loss0 = pcast(jnp.zeros(()), ("pipe",), to="varying")

            def run_tick(state, t):
                return lax.switch(idx, branches, state, t)

            if self.remat:  # O(1) residuals per tick; ppermute stays out
                run_tick = jax.checkpoint(run_tick)

            def tick(carry, t):
                state, loss_sum = carry
                out, l = run_tick(state, t)
                m_out = t - (S - 1)
                loss_sum = loss_sum + jnp.where(
                    (idx == S - 1) & (m_out >= 0), l, 0.0)
                state = lax.ppermute(out, "pipe", perm)
                return (state, loss_sum), None

            (_, loss_sum), _ = lax.scan(
                tick, (state0, loss0), jnp.arange(M + S - 1))
            # LOCAL loss only (nonzero on the last stage); grads are
            # nonzero only for the executing stage's branch
            return loss_sum / M

        if self._hetero_sharded:
            return self._finish_hetero_sharded_step(schedule_loss, cfg, S)

        def spmd(tree, xs, ys):
            idx = lax.axis_index("pipe")
            loss, grads = jax.value_and_grad(
                lambda tr: schedule_loss(lambda s: tr, xs, ys, idx))(tree)
            # the psum reassembles the full tree without double counting
            return lax.psum(loss, "pipe"), lax.psum(grads, "pipe")

        repl = P()
        sharded = shard_map(spmd, mesh=self._mesh,
                            in_specs=(repl, repl, repl),
                            out_specs=(repl, repl), check_vma=False)
        reg_layers = [l for ls in stage_layers for l in ls if l.has_params()]

        def reg_fn(tree):
            r = jnp.zeros(())
            for l in reg_layers:
                r = r + l.reg_score(tree.get(l.name, {}))
            return r

        lr_overrides = self._lr_overrides

        def step(tree, opt_state, it, xs, ys):
            loss, grads = sharded(tree, xs, ys)
            reg_val, reg_g = jax.value_and_grad(reg_fn)(tree)
            grads = {k: v for k, v in grads.items() if v}
            grads = jax.tree_util.tree_map(
                jnp.add, grads, {k: reg_g[k] for k in grads})
            updates, new_opt = upd.update(cfg, grads, opt_state, it,
                                          lr_overrides, params=tree)
            new_tree = {
                k: (upd.apply_updates(v, u)
                    if (u := updates.get(k)) else v)
                for k, v in tree.items()
            }
            return new_tree, new_opt, loss + reg_val

        return instrument(jax.jit(step, donate_argnums=(0, 1)),
                          "PipelineParallelTrainingMaster.hetero_step", argnums=(2, 3, 4))

    def _finish_hetero_sharded_step(self, schedule_loss, cfg, S):
        """Sharded-param variant: each device owns one [Pmax] f32 row
        holding its stage's raveled params; branch s unflattens ITS row.
        Grads w.r.t. the local row arrive via the ppermute-transpose chain
        with support only on the owning device — no grad psum at all (the
        dp all-reduce's absence is the point: pipe-axis traffic is
        activations + their cotangents only).  The elementwise updater then
        acts directly on the sharded [S, Pmax] rows (one pseudo-layer),
        bitwise-identical to per-layer updates because sgd/nesterov/adam/
        etc. are per-element — guarded upstream: no lr overrides, no
        gradient normalization."""
        stage_layers = self.stage_layers

        def spmd(flat_rows, xs, ys):
            idx = lax.axis_index("pipe")

            def local_total(flat):
                # branch s unflattens MY row as stage s's tree INSIDE the
                # switch branch — correct on the one device whose idx == s,
                # never materialized elsewhere
                loss = schedule_loss(
                    lambda s: self._hetero_stage_tree(s, flat), xs, ys, idx)

                def make_reg(s):
                    def rb(flat):
                        tree = self._hetero_stage_tree(s, flat)
                        r = jnp.zeros(())
                        for l in stage_layers[s]:
                            if l.has_params():
                                r = r + l.reg_score(tree.get(l.name, {}))
                        return r
                    return rb

                return loss + lax.switch(
                    idx, [make_reg(s) for s in range(S)], flat)

            loss, gflat = jax.value_and_grad(local_total)(flat_rows[0])
            return lax.psum(loss, "pipe"), gflat[None]

        sharded = shard_map(spmd, mesh=self._mesh,
                            in_specs=(P("pipe"), P(), P()),
                            out_specs=(P(), P("pipe")), check_vma=False)

        def step(flat, opt_state, it, xs, ys):
            loss, gflat = sharded(flat, xs, ys)
            updates, new_opt = upd.update(
                cfg, {"_pipe": {"w": gflat}}, opt_state, it, {},
                params={"_pipe": {"w": flat}})
            return flat - updates["_pipe"]["w"], new_opt, loss

        return instrument(jax.jit(step, donate_argnums=(0, 1)),
                          "PipelineParallelTrainingMaster.hetero_step", argnums=(2, 3, 4))

    def _execute_hetero(self, net, iterator, res=None):
        from deeplearning4j_tpu.resilience import preemption_requested

        M = self.n_microbatches
        if self._hetero_sharded:
            # flat f32 rows, one per stage, device s owns row s — params
            # AND optimizer state partitioned ~1/S per device
            tree = jax.device_put(self._hetero_flatten(net.params),
                                  self._row_sharding)
            opt_state = {
                k: {"_pipe": {"w": jax.device_put(
                    self._hetero_flatten(per_layer, missing_ok=True),
                    self._row_sharding)}}
                for k, per_layer in net.updater_state.items()}
        else:
            tree = jax.device_put(net.params, self._repl_sharding)
            opt_state = jax.device_put(net.updater_state,
                                       self._repl_sharding)
        # the ledger makes the sharded-vs-replicated fast-path decision
        # visible: downgraded runs show replication_factor ≈ n_stages
        shardstats.record_ledger(
            "pipeline_master", {"params": tree, "updater_state": opt_state},
            data_axis_size=self.n_stages)

        def unflatten_back():
            if self._hetero_sharded:
                net.params.update(self._hetero_unflatten_host(tree))
                for k in net.updater_state:
                    net.updater_state[k].update(self._hetero_unflatten_host(
                        opt_state[k]["_pipe"]["w"]))
            else:
                net.params = tree
                net.updater_state = opt_state

        stopped = False
        for ds in iterator:
            if res is not None and res.skip_batch():
                continue   # auto-resume: batch already covered by the ckpt
            if preemption_requested():
                stopped = True
                break
            if ds.features_mask is not None or ds.labels_mask is not None:
                raise ValueError(
                    "pipeline master does not support masked batches")
            x = np.asarray(ds.features)
            y = np.asarray(ds.labels)
            if len(x) % M:
                raise ValueError(f"batch {len(x)} not divisible by "
                                 f"{M} microbatches")
            xs = jnp.asarray(x.reshape((M, len(x) // M) + x.shape[1:]))
            ys = jnp.asarray(y.reshape((M, len(y) // M) + y.shape[1:]))
            key = (xs.shape, ys.shape)
            if key not in self._compiled_steps:
                self._compiled_steps[key] = self._make_hetero_step(
                    net, xs.shape[1:], xs.dtype)
            with step_guard("pipeline_step", component="pipeline_master",
                            iteration=net.iteration):
                with self._phases.phase("dispatch"):
                    if res is not None:

                        def dispatch(tree=tree, opt_state=opt_state):
                            return self._compiled_steps[key](
                                tree, opt_state,
                                jnp.asarray(float(net.iteration)), xs, ys)

                        tree, opt_state, loss = res.step(
                            dispatch, net.iteration, net=net)
                    else:
                        tree, opt_state, loss = self._compiled_steps[key](
                            tree, opt_state,
                            jnp.asarray(float(net.iteration)), xs, ys)
            net.score_value = loss
            net.iteration += 1
            self._phases.steps += 1
            notify_listeners(net, len(x))
            if res is not None and res.cm is not None:
                trigger = res.cm.due(net.iteration)
                if trigger is not None:
                    unflatten_back()
                    res.cm.save(net, trigger=trigger)
        unflatten_back()
        if stopped and res is not None:
            res.on_preempt(net)

    # --- facade <-> pipeline param tree conversion (keys: pfx/ blk/ sfx/)
    def _stack_tree(self, per_layer: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        for l in self._pfx:
            if l.name in per_layer:
                out[f"pfx/{l.name}"] = per_layer[l.name]
        for j in range(len(self._template)):
            trees = [per_layer.get(g[j].name, {}) for g in self._stage_groups]
            if trees[0]:
                out[f"blk/{j}"] = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *trees)
        for l in self._sfx:
            if l.name in per_layer:
                out[f"sfx/{l.name}"] = per_layer[l.name]
        return out

    def _unstack_tree(self, tree: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        for k, v in tree.items():
            kind, _, tail = k.partition("/")
            if kind == "blk":
                j = int(tail)
                for s, g in enumerate(self._stage_groups):
                    out[g[j].name] = jax.tree_util.tree_map(
                        lambda a: a[s], v)
            else:
                out[tail] = v
        return out

    def _make_compiled_step(self, net, x_mb_shape, x_dtype):
        S = self.n_stages
        M = self.n_microbatches
        mesh = self._mesh
        cfg = self._upd_cfg
        pfx, sfx, template = self._pfx, self._sfx, self._template
        out_layer = sfx[-1]

        def prefix_fwd(tree, a):
            for l in pfx:
                a, _ = l.apply(tree.get(f"pfx/{l.name}", {}), {}, a,
                               train=True, rng=None)
            return a

        def stage_fwd(blk, a):
            for j, l in enumerate(template):
                a, _ = l.apply(blk.get(f"blk/{j}", {}), {}, a,
                               train=True, rng=None)
            return a

        def suffix_loss(tree, a, y):
            for l in sfx[:-1]:
                a, _ = l.apply(tree.get(f"sfx/{l.name}", {}), {}, a,
                               train=True, rng=None)
            return out_layer.score(tree[f"sfx/{out_layer.name}"], a, y)

        # static activation shape: block io shape == prefix output shape
        pfx_tree = {k: v for k, v in self._stack_tree(net.params).items()
                    if k.startswith("pfx/")}
        probe = jax.eval_shape(prefix_fwd, pfx_tree,
                               jax.ShapeDtypeStruct(x_mb_shape, x_dtype))

        def spmd(pfx_p, blk_p, sfx_p, xs, ys):
            idx = lax.axis_index("pipe")
            blk_local = jax.tree_util.tree_map(lambda a: a[0], blk_p)
            perm = [(i, i + 1) for i in range(S - 1)]

            def local_loss(pfx_p, blk_local, sfx_p):
                state0 = jnp.zeros(probe.shape, probe.dtype)
                state0 = pcast(state0, ("pipe",), to="varying")

                def run_tick(state, t):
                    a0 = prefix_fwd(pfx_p, xs[jnp.clip(t, 0, M - 1)])
                    inp = jnp.where(idx == 0, a0, state)
                    outv = stage_fwd(blk_local, inp)
                    m_out = t - (S - 1)
                    l = suffix_loss(sfx_p, outv,
                                    ys[jnp.clip(m_out, 0, M - 1)])
                    return outv, l

                if self.remat:  # O(1) residuals/tick; ppermute stays out
                    run_tick = jax.checkpoint(run_tick)

                def tick(carry, t):
                    state, loss_sum = carry
                    outv, l = run_tick(state, t)
                    m_out = t - (S - 1)
                    loss_sum = loss_sum + jnp.where(
                        (idx == S - 1) & (m_out >= 0), l, 0.0)
                    state = lax.ppermute(outv, "pipe", perm)
                    return (state, loss_sum), None

                loss0 = pcast(jnp.zeros(()), ("pipe",), to="varying")
                (_, loss_sum), _ = lax.scan(
                    tick, (state0, loss0), jnp.arange(M + S - 1))
                # LOCAL loss only (nonzero on the last stage).  Differentiating
                # the psum'd total would double-count: every device's output
                # would back-propagate cotangents into every stage's params.
                return loss_sum / M

            loss, (gp, gb, gs) = jax.value_and_grad(
                local_loss, argnums=(0, 1, 2))(pfx_p, blk_local, sfx_p)
            loss = lax.psum(loss, "pipe")
            gp = lax.psum(gp, "pipe")
            gs = lax.psum(gs, "pipe")
            gb = jax.tree_util.tree_map(lambda a: a[None], gb)
            return loss, gp, gb, gs

        repl, piped = P(), P("pipe")
        sharded = shard_map(
            spmd, mesh=mesh,
            in_specs=(repl, piped, repl, repl, repl),
            out_specs=(repl, repl, piped, repl),
            check_vma=False,
        )
        reg_layers = ([(f"pfx/{l.name}", l) for l in pfx if l.has_params()]
                      + [(f"blk/{j}", l) for j, l in enumerate(template)
                         if l.has_params()]
                      + [(f"sfx/{l.name}", l) for l in sfx if l.has_params()])

        def reg_fn(tree):
            r = jnp.zeros(())
            for key, l in reg_layers:
                if key in tree:
                    r = r + l.reg_score(tree[key])
            return r

        def step(tree, opt_state, it, xs, ys):
            pfx_p = {k: v for k, v in tree.items() if k.startswith("pfx/")}
            blk_p = {k: v for k, v in tree.items() if k.startswith("blk/")}
            sfx_p = {k: v for k, v in tree.items() if k.startswith("sfx/")}
            loss, gp, gb, gs = sharded(pfx_p, blk_p, sfx_p, xs, ys)
            reg_val, reg_g = jax.value_and_grad(reg_fn)(tree)
            grads = {**gp, **gb, **gs}
            grads = jax.tree_util.tree_map(jnp.add, grads,
                                           {k: reg_g[k] for k in grads})
            updates, new_opt = upd.update(cfg, grads, opt_state, it, {},
                                          params={k: tree[k] for k in grads})
            new_tree = {
                k: (upd.apply_updates(v, updates[k]) if k in updates else v)
                for k, v in tree.items()
            }
            return new_tree, new_opt, loss + reg_val

        return instrument(jax.jit(step, donate_argnums=(0, 1)),
                          "PipelineParallelTrainingMaster.compiled_step", argnums=(2, 3, 4))

    def _execute_compiled(self, net, iterator, res=None):
        from deeplearning4j_tpu.resilience import preemption_requested

        M = self.n_microbatches
        tree = self._stack_tree(net.params)
        opt_state = {slot: self._stack_tree(per_layer)
                     for slot, per_layer in net.updater_state.items()}
        place = lambda t: {
            k: jax.device_put(v, self._blk_sharding if k.startswith("blk/")
                              else self._repl_sharding)
            for k, v in t.items()}
        tree = place(tree)
        opt_state = {slot: place(t) for slot, t in opt_state.items()}
        # ledger over the placed trees: blk/ leaves are [S, ...] sharded
        # over 'pipe' (factor 1), pfx/sfx replicated on every stage device
        shardstats.record_ledger(
            "pipeline_master", {"params": tree, "updater_state": opt_state},
            data_axis_size=self.n_stages)

        def unstack_back():
            net.params.update(self._unstack_tree(tree))
            for slot, t in opt_state.items():
                net.updater_state[slot].update(self._unstack_tree(t))

        stopped = False
        for ds in iterator:
            if res is not None and res.skip_batch():
                continue   # auto-resume: batch already covered by the ckpt
            if preemption_requested():
                stopped = True
                break
            if ds.features_mask is not None or ds.labels_mask is not None:
                raise ValueError("pipeline master does not support masked batches")
            x = np.asarray(ds.features)
            y = np.asarray(ds.labels)
            if len(x) % M:
                raise ValueError(f"batch {len(x)} not divisible by "
                                 f"{M} microbatches")
            xs = jnp.asarray(x.reshape((M, len(x) // M) + x.shape[1:]))
            ys = jnp.asarray(y.reshape((M, len(y) // M) + y.shape[1:]))
            key = (xs.shape, ys.shape)  # probe shape is batch-dependent
            if key not in self._compiled_steps:
                self._compiled_steps[key] = self._make_compiled_step(
                    net, xs.shape[1:], xs.dtype)
            with step_guard("pipeline_step", component="pipeline_master",
                            iteration=net.iteration):
                with self._phases.phase("dispatch"):
                    if res is not None:

                        def dispatch(tree=tree, opt_state=opt_state):
                            return self._compiled_steps[key](
                                tree, opt_state,
                                jnp.asarray(float(net.iteration)), xs, ys)

                        tree, opt_state, loss = res.step(
                            dispatch, net.iteration, net=net)
                    else:
                        tree, opt_state, loss = self._compiled_steps[key](
                            tree, opt_state,
                            jnp.asarray(float(net.iteration)), xs, ys)
            net.score_value = loss  # device scalar; fetched lazily on read
            net.iteration += 1
            self._phases.steps += 1
            notify_listeners(net, len(x))
            if res is not None and res.cm is not None:
                trigger = res.cm.due(net.iteration)
                if trigger is not None:
                    # unstacking the whole tree is the fold-back cost; paid
                    # only when a save is actually due
                    unstack_back()
                    res.cm.save(net, trigger=trigger)
        unstack_back()
        if stopped and res is not None:
            res.on_preempt(net)

    # ---------------------------------------------------------------- train
    def execute_training(self, net, iterator):
        from deeplearning4j_tpu.resilience import FitResilience

        res = None
        if self.checkpoint_manager is not None or self.retry_policy is not None:
            res = FitResilience("pipeline_master", self.checkpoint_manager,
                                self.retry_policy, net=net)
        intro_held = None
        if getattr(net.conf, "introspection", None) is not None:
            # the pipeline master splits updater state per stage by LAYER
            # name; the layerless __introspect__ subtree cannot shard that
            # way, so introspection does not cover this master yet — park
            # the subtree for the duration of the fit instead of feeding
            # it into the per-stage split (docs/observability.md)
            from deeplearning4j_tpu.observability import introspection

            intro_held = net.updater_state.pop(introspection.STATE_KEY, None)
        num_held = None
        if getattr(net.conf, "numerics", None) is not None:
            # the layerless __numerics__ precision-ledger subtree is
            # parked for the same reason — stale over a pipeline fit
            from deeplearning4j_tpu.observability import numerics

            num_held = net.updater_state.pop(numerics.STATE_KEY, None)
        try:
            return self._execute_with_master(net, iterator, res)
        finally:
            if intro_held is not None:
                net.updater_state[introspection.STATE_KEY] = intro_held
            if num_held is not None:
                net.updater_state[numerics.STATE_KEY] = num_held

    def _execute_with_master(self, net, iterator, res):
        from deeplearning4j_tpu.resilience import preemption_requested

        if not self._built:
            self._build(net)
        if self._mode == "compiled":
            if self._compiled_kind == "hetero":
                return self._execute_hetero(net, iterator, res)
            return self._execute_compiled(net, iterator, res)
        S = len(self.stages)
        # place each stage's params + updater state on its device
        stage_params = [
            jax.device_put(self._stage_params(net, s), self.devices[s])
            for s in range(S)
        ]
        stage_upd = [
            jax.device_put(
                {slot: {n: tree[n] for n in stage_params[s] if n in tree}
                 for slot, tree in net.updater_state.items()},
                self.devices[s])
            for s in range(S)
        ]
        # per-STAGE sharding ledger: each stage's rows sum to the
        # single-device totals (the memory win pipeline placement buys)
        shardstats.record_ledger("pipeline_master", {
            **{f"params_stage{s}": stage_params[s] for s in range(S)},
            **{f"updater_state_stage{s}": stage_upd[s] for s in range(S)},
        })

        if self._workers is None:
            self._workers = WorkerTelemetry("pipeline_master")
        for ds in iterator:
            if res is not None and res.skip_batch():
                continue   # auto-resume: batch already covered by the ckpt
            if preemption_requested():
                self._merge_back(net, stage_params, stage_upd)
                if res is not None:
                    res.on_preempt(net)
                return
            with step_guard("pipeline_step", component="pipeline_master",
                            iteration=net.iteration):
                if res is not None:
                    loss = res.step(
                        lambda: self._train_batch(net, ds, stage_params,
                                                  stage_upd),
                        net.iteration, net=net)
                else:
                    loss = self._train_batch(net, ds, stage_params, stage_upd)
            net.score_value = loss  # device scalar; fetched lazily on read
            net.iteration += 1
            self._phases.steps += 1
            notify_listeners(net, len(ds))
            if res is not None and res.cm is not None:
                trigger = res.cm.due(net.iteration)
                if trigger is not None:
                    self._merge_back(net, stage_params, stage_upd)
                    res.cm.save(net, trigger=trigger)
        self._merge_back(net, stage_params, stage_upd)

    def _merge_back(self, net, stage_params, stage_upd) -> None:
        """Merge per-stage params/updater state back into the facade (loop
        end, due checkpoint saves, preemption stop)."""
        S = len(self.stages)
        for s in range(S):
            for name, p in stage_params[s].items():
                net.params[name] = jax.device_put(p, self.devices[0])
        for slot in net.updater_state:
            merged = {}
            for s in range(S):
                merged.update(stage_upd[s][slot])
            net.updater_state[slot] = {
                n: jax.device_put(v, self.devices[0])
                for n, v in merged.items()}

    def _train_batch(self, net, ds, stage_params, stage_upd):
        if ds.features_mask is not None or ds.labels_mask is not None:
            raise ValueError("pipeline master does not support masked batches")
        phase_t0 = self._phases.totals()
        S = len(self.stages)
        M = self.n_microbatches
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        if len(x) % M:
            raise ValueError(f"batch {len(x)} not divisible by "
                             f"{M} microbatches")
        xs = jnp.split(x, M)
        ys = jnp.split(y, M)

        pullbacks = [[None] * S for _ in range(M)]
        losses = [None] * M
        grads = [None] * S

        def forward(m):
            # async dispatch overlaps (m, s) with (m+1, s-1); the per-stage
            # timers measure host DISPATCH time per stage (device compute is
            # async), which is what serializes the orchestrated schedule
            a = jax.device_put(xs[m], self.devices[0])
            for s in range(S - 1):
                with self._phases.phase(f"stage{s}_fwd"):
                    a, vjp = jax.vjp(self._stage_fwds[s], stage_params[s], a)
                pullbacks[m][s] = vjp
                a = jax.device_put(a, self.devices[s + 1])
            y_m = jax.device_put(ys[m], self.devices[S - 1])
            with self._phases.phase(f"stage{S - 1}_fwd"):
                loss_m, vjp = jax.vjp(self._last_stage, stage_params[S - 1],
                                      a, y_m)
            pullbacks[m][S - 1] = vjp
            losses[m] = loss_m

        def backward(m):
            seed = jnp.ones((), losses[m].dtype) / M
            with self._phases.phase(f"stage{S - 1}_bwd"):
                gp, ga, _gy = pullbacks[m][S - 1](seed)
            grads[S - 1] = gp if grads[S - 1] is None else jax.tree_util.tree_map(
                jnp.add, grads[S - 1], gp)
            for s in range(S - 2, -1, -1):
                ga = jax.device_put(ga, self.devices[s])
                with self._phases.phase(f"stage{s}_bwd"):
                    gp, ga = pullbacks[m][s](ga)
                grads[s] = gp if grads[s] is None else jax.tree_util.tree_map(
                    jnp.add, grads[s], gp)
            pullbacks[m] = [None] * S   # release stashed activations

        if self.schedule == "1f1b":
            # PipeDream-flush: after the S-1-tick fill, each microbatch's
            # backward follows its forward — at most S pullbacks live at
            # once (vs M for GPipe), same (S-1)/(M+S-1) bubble
            for t in range(M + S - 1):
                if t < M:
                    forward(t)
                if t - (S - 1) >= 0:
                    backward(t - (S - 1))
        else:
            # GPipe: all forwards (fill), then all backwards (drain)
            for m in range(M):
                forward(m)
            for m in range(M):
                backward(m)

        # regularization value+gradients + updater apply, per stage on-device
        it = jnp.asarray(float(net.iteration))
        reg_vals = []
        for s in range(S):
            reg_val, reg_grad = self._reg_fns[s](stage_params[s])
            reg_vals.append(reg_val)  # no host sync inside the dispatch loop
            g = jax.tree_util.tree_map(jnp.add, grads[s], reg_grad)
            updates, stage_upd[s] = upd.update(
                self._upd_cfg, g, stage_upd[s], it, self._lr_overrides,
                params=stage_params[s])
            stage_params[s] = {
                ln: (upd.apply_updates(stage_params[s][ln], u)
                     if (u := updates.get(ln)) else stage_params[s][ln])
                for ln in stage_params[s]
            }
        # per-stage dispatch time this batch (phase-total deltas) -> the
        # worker families + straggler detector; an unbalanced stage split
        # shows up as worker "stage<s>" straggling
        if self._workers is not None:
            t1 = self._phases.totals()
            for s in range(S):
                fwd = (t1.get(f"stage{s}_fwd", 0.0)
                       - phase_t0.get(f"stage{s}_fwd", 0.0))
                bwd = (t1.get(f"stage{s}_bwd", 0.0)
                       - phase_t0.get(f"stage{s}_bwd", 0.0))
                self._workers.observe(f"stage{s}", fwd + bwd,
                                      phases={"fwd": fwd, "bwd": bwd})

        # score matches serial _loss_fn: data loss + regularization penalty
        return (sum(jax.device_get(l) for l in losses) / M
                + sum(float(r) for r in reg_vals))
