"""Distributed training strategy SPI + masters — the Spark scaleout redesign.

Reference: ``spark/dl4j-spark/.../api/TrainingMaster.java:27`` (strategy
object owning "how fit() distributes") and
``impl/paramavg/ParameterAveragingTrainingMaster.java:336-366,628-645``
(driver-centric: broadcast params -> executors train avgFreq minibatches ->
RDD.aggregate tree-reduce -> divide -> repeat).

TPU-native redesign: the driver never touches per-step data.  Training is
in-graph SPMD over a ``jax.sharding.Mesh`` spanning all chips (multi-host:
same code after ``jax.distributed.initialize`` — the mesh covers every
process's local devices and XLA routes collectives over ICI within a slice
and DCN across slices).  Two strategies:

- ``SyncTrainingMaster`` — synchronous DP: ONE jitted step per global batch;
  params replicated, batch sharded over the 'data' axis; the gradient
  all-reduce is inserted by XLA because the loss averages over the sharded
  batch.  This is the "modern" path and the perf-bench path: gradient sync
  costs one all-reduce per step riding ICI.
- ``ParameterAveragingTrainingMaster`` — reproduces the reference's
  averaging semantics (train ``averaging_frequency`` local minibatches per
  worker, then average params and optionally updater state), for capability
  parity and the distributed-vs-local equivalence tests
  (``TestCompareParameterAveragingSparkVsSingleMachine``).

The ``TrainingMaster`` SPI is kept as the strategy seam, like the reference.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.observability import (
    PhaseTimers, WorkerTelemetry, crash_dump, instrument, step_guard,
)
from deeplearning4j_tpu.observability import shardstats
from deeplearning4j_tpu.optimize import updaters as upd
from deeplearning4j_tpu.parallel import zero as zero_mod
from deeplearning4j_tpu.parallel.elastic import ElasticConfig, ElasticController


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (reference: Spark cluster + broadcast;
    here: jax.distributed — one call per host, then every jit spans the
    global mesh)."""
    jax.distributed.initialize(coordinator_address, num_processes, process_id)


class PhaseStats(PhaseTimers):
    """Phase-timed distributed training stats (≙ ``CommonSparkTrainingStats
    .java`` / ``ParameterAveragingTrainingMasterStats.java``).

    Since the unified-telemetry refactor this is a thin alias over
    ``observability.PhaseTimers``: the ``phase()`` / ``steps`` /
    ``as_dict()`` surface is unchanged, but every timed phase ALSO lands in
    the process-wide metrics registry as
    ``dl4j_phase_seconds{component=..., phase=...}`` so /metrics scrapes
    and bench snapshots see it (migration notes: docs/observability.md)."""

    def __init__(self, enabled: bool = True,
                 component: str = "training_master"):
        super().__init__(component, enabled=enabled)


class TrainingMaster:
    """Strategy SPI (reference ``TrainingMaster.java:27``)."""

    def execute_training(self, net, iterator) -> None:
        raise NotImplementedError

    def training_stats(self) -> Dict[str, Any]:
        return {}


class SyncTrainingMaster(TrainingMaster):
    """Per-step synchronous data parallelism over the mesh.

    Each global batch of size B is sharded into B/K per-device shards; the
    jitted step computes local grads and XLA all-reduces them (mean over the
    global batch) before the updater applies — one collective per step.
    """

    def __init__(self, mesh: Optional[Mesh] = None, batch_size: Optional[int] = None,
                 prefetch_size: int = 2, collect_stats: bool = False,
                 checkpoint_manager=None, retry_policy=None, elastic=False,
                 update_sharding: str = zero_mod.REPLICATED):
        self.mesh = mesh or backend.default_mesh()
        self.batch_size = batch_size
        self.prefetch_size = prefetch_size
        self.collect_stats = collect_stats
        # ZeRO update sharding (arXiv 2004.13336, docs/PARALLELISM.md
        # "ZeRO"): with update_sharding="zero" the gradients are
        # reduce-scattered instead of all-reduced, each device updates
        # only its 1/K shard of the params + updater state, and the
        # params are all-gathered for the next forward — same wire
        # bytes, 1/K the persistent optimizer memory.  Default
        # "replicated" keeps today's all-reduce + replicated update.
        self.update_sharding = zero_mod.validate_mode(update_sharding,
                                                      self.mesh)
        self._zero_layout = (zero_mod.ZeroLayout(self.mesh)
                             if self.update_sharding == zero_mod.ZERO
                             else None)
        # elasticity (docs/resilience.md "Elasticity"): a dead/hung/
        # straggling data shard is evicted by zeroing its rows in the
        # labels mask — the masked loss mean renormalizes over the healthy
        # rows (losses.score divides by sum(mask)), so the gradient is the
        # DeepSpark-style average over the degraded worker set.  Params
        # stay replicated, so re-admission needs no catch-up: the mask
        # just flips back.  Pass True or an ElasticConfig.
        self._elastic: Optional[ElasticController] = None
        if elastic is not False and elastic is not None:
            ecfg = elastic if isinstance(elastic, ElasticConfig) else ElasticConfig()
            self.collect_stats = True        # straggler verdicts need stats
            slots = self._data_slot_devices()
            self._elastic = ElasticController(
                "sync_master", [f"d{s[0].id}" for s in slots], config=ecfg,
                aliases={f"d{s[0].id}": [f"d{d.id}" for d in s]
                         for s in slots})
        # resilience wiring (docs/resilience.md): auto-resume on entry,
        # boundary saves, clean preemption stop, transient step retry
        self.checkpoint_manager = checkpoint_manager
        self.retry_policy = retry_policy
        # step_time_ms is a bounded window (last 1024) — stats stay O(1)
        # however long training runs; PhaseStats carries the full aggregates
        self._stats: Dict[str, Any] = {
            "steps": 0, "step_time_ms": collections.deque(maxlen=1024)}
        # per-step phase timers only when stats collection is requested —
        # the default hot loop stays timer-free.  Phase mapping vs the
        # reference: fetch≙split/repartition, place≙broadcast, dispatch =
        # gradient compute + the in-graph all-reduce (the reference's
        # aggregate), device_sync = host sync on the step result.
        self._phases = PhaseStats(enabled=collect_stats,
                                  component="sync_master")
        # per-device step time (published only under collect_stats — the
        # per-shard arrival measurement IS a device sync, which that mode
        # already pays in its device_sync phase)
        self._workers: Optional[WorkerTelemetry] = None
        self._step = None
        self._stab_rt = None          # StabilityRuntime (net.conf.stability)
        self._stab_workers: list = []  # data-slot worker ids ("d<id>")

    @property
    def elastic(self) -> Optional[ElasticController]:
        """The elasticity state machine (None unless ``elastic=`` was
        passed) — ``elastic.summary()`` is the operator view."""
        return self._elastic

    def _data_slot_devices(self):
        """Devices grouped by data-axis slot: ``order[k]`` is EVERY device
        holding slot ``k`` of the [K]-sharded batch (one on a pure-DP
        mesh, model*seq of them on a composed mesh).  The first member
        names the slot (``d<id>``) for the elastic controller; the rest
        become its aliases, so telemetry verdicts and injected faults on
        ANY member evict the whole slot."""
        K = self.mesh.shape[backend.AXIS_DATA]
        sh = NamedSharding(self.mesh, P(backend.AXIS_DATA))
        order = [[] for _ in range(K)]
        # the GLOBAL device map: on a multi-host mesh the addressable map
        # only covers this host's devices, which would leave remote hosts'
        # slots empty (and slot naming must agree across processes anyway)
        for dev, idx in sh.devices_indices_map((K,)).items():
            sl = idx[0] if idx else slice(None)
            for i in range(*sl.indices(K)):
                order[i].append(dev)
        for slot in order:
            slot.sort(key=lambda d: d.id)
        return order

    def _evicted_labels_mask(self, ds, emask, K: int):
        """Labels mask with the evicted data slots' rows zeroed (existing
        mask respected).  The masked score normalizes by ``sum(mask)``, so
        zeroed rows renormalize the global gradient mean over the healthy
        rows — eviction without touching the compiled collective."""
        B = len(ds)
        rw = np.repeat(np.asarray(emask, np.float32), B // K)
        lm = ds.labels_mask
        if lm is None:
            return rw.reshape((B,) + (1,) * (ds.labels.ndim - 2))
        lm = np.asarray(lm)
        return lm * rw.reshape((B,) + (1,) * (lm.ndim - 1))

    def _param_layout(self, net):
        """Sharding (single or per-param pytree) for the parameters.  Base:
        fully replicated.  TensorParallelTrainingMaster overrides this with
        model-axis shardings — the jitted step is otherwise identical."""
        return NamedSharding(self.mesh, P())

    def _build(self, net):
        from deeplearning4j_tpu.observability import introspection, numerics
        from deeplearning4j_tpu.resilience import stability

        cfg = net.conf.updater
        policy = net.conf.stability
        plan = introspection.plan_for(net)
        nplan = numerics.plan_for(net)
        lr_overrides = {
            l.name: l.learning_rate for l in net.layers if l.learning_rate is not None
        }
        mesh = self.mesh
        K = mesh.shape[backend.AXIS_DATA]
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P(backend.AXIS_DATA))
        players = self._param_layout(net)
        # updater state mirrors the param tree per slot ({"m": ..., "v": ...})
        # but only over TRAINABLE layers — restrict to the state's own keys.
        # The stability, introspection and numerics subtrees are plain
        # scalars/small vectors: replicated, like the rest of the non-param
        # step state.
        if isinstance(players, dict) and net.updater_state:
            ulayers: Any = {
                slot: (repl if slot in (stability.STATE_KEY,
                                        introspection.STATE_KEY,
                                        numerics.STATE_KEY)
                       else {ln: players[ln] for ln in tree})
                for slot, tree in net.updater_state.items()
            }
        elif isinstance(players, dict):
            ulayers = repl
        else:
            ulayers = players

        def step(params, upd_state, net_state, iteration, x, y, rng, fm, lm):
            nstate = None
            if nplan is not None:
                nstate, upd_state = numerics.split_state(upd_state)
            if plan is not None:
                _, upd_state = introspection.split_state(upd_state)
            now = numerics.collect_now(nplan, iteration)
            kw = ({"collect_acts": True}
                  if numerics.wants_acts(plan, nplan) else {})
            if kw and now is not None:
                kw["numerics_now"] = now
            if policy is None:
                (loss, aux), grads = jax.value_and_grad(net._loss_fn, has_aux=True)(
                    params, net_state, x, y, rng, fm, lm, None, **kw
                )
                new_ns, _, act_stats = numerics.unpack_aux(plan, nplan, aux)
                grads = {k: v for k, v in grads.items() if v}
                updates, new_us = upd.update(cfg, grads, upd_state, iteration,
                                             lr_overrides, params=params)
                new_params = {
                    ln: (upd.apply_updates(params[ln], u)
                         if (u := updates.get(ln)) else params[ln])
                    for ln in params
                }
                # the gradients here are already the all-reduced global
                # mean, so the per-layer norms are the cluster-wide view
                # (replicated across devices)
                introspection.attach(
                    new_us, plan, grads=grads, params=params,
                    new_params=new_params, iteration=iteration,
                    act_stats=act_stats)
                numerics.attach(
                    new_us, nplan, grads=grads, iteration=iteration,
                    act_stats=act_stats, prev=nstate, now=now)
                return new_params, new_us, new_ns, loss
            # stability engine (resilience/stability.py): poisoned ROWS are
            # zeroed before the forward (NaN activations poison the
            # backward even under a zero cotangent) and renormalized out
            # of the masked loss mean — the global gradient is EXACTLY the
            # mean over the healthy rows, the sync-master analog of the
            # wrapper's [K] weight mask.  A residual non-finite verdict
            # (fp overflow in healthy data) still skips the whole step
            # device-side.  The caller guarantees lm is always an array
            # (all-ones when no mask), so poison flips values, not the
            # pytree — zero recompiles.
            stab, inner = stability.split_state(upd_state)
            row_ok = stability.finite_rows(x, y)
            x = stability.zero_nonfinite_rows(x, row_ok)
            y = stability.zero_nonfinite_rows(y, row_ok)
            lm = lm * row_ok.reshape((row_ok.shape[0],)
                                     + (1,) * (lm.ndim - 1))
            (_, (loss, aux)), grads = jax.value_and_grad(
                stability.scaled_loss(net._loss_fn, stab), has_aux=True)(
                params, net_state, x, y, rng, fm, lm, None, **kw)
            new_ns, _, act_stats = numerics.unpack_aux(plan, nplan, aux)
            # an all-rows-poisoned batch yields a zero loss and zero
            # gradients — finite, but updating would still decay Adam
            # moments toward the pad; veto it
            new_params, new_us, new_ns, _ = stability.apply_guarded_update(
                policy, cfg, stab, inner, params, net_state, loss, grads,
                new_ns, iteration, lr_overrides,
                extra_ok=jnp.sum(row_ok) > 0)
            introspection.attach(
                new_us, plan, grads=grads, params=params,
                new_params=new_params, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"])
            numerics.attach(
                new_us, nplan, grads=grads, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"],
                prev=nstate, now=now)
            return (new_params, new_us, new_ns, loss,
                    stability.slot_poison_flags(row_ok, K))

        in_shardings = (players, ulayers, repl, repl, data, data, repl, data,
                        data)
        out_shardings = (players, ulayers, repl, repl)
        if policy is not None:
            out_shardings = out_shardings + (repl,)
        self._step = instrument(jax.jit(
            step,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=(0, 1, 2),
        ), f"{type(self).__name__}.step", argnums=(3, 4, 5, 6, 7, 8))
        self._data_sharding = data
        self._repl_sharding = repl
        self._params_layout = players
        self._upd_layout = ulayers

    def _build_zero(self, net):
        """The ZeRO-sharded step (update_sharding="zero"): forward +
        backward run per data shard inside a ``shard_map`` — each device
        all-gathers the sharded params, computes its LOCAL gradient
        contribution (the per-shard loss weighted by that shard's share
        of the global normalizer, so the psum of contributions is
        exactly the replicated step's global-mean gradient, masked
        normalization and regularization included), and reduce-scatters
        it — then the updater, the stability guard and introspection run
        UNCHANGED on the sharded trees under GSPMD (per-layer
        normalization norms and finiteness reductions come out global
        automatically).  Params and Adam moments live sharded; the
        ``__stability__`` / ``__introspect__`` subtrees stay replicated
        (the choice is recorded in the sharding ledger's notes).  The
        ``__numerics__`` precision-ledger subtree is carried through
        UNCHANGED (stale): its max-abs / fraction stats do not merge
        correctly across per-shard activation views (a pmean of
        per-shard maxes is not the global max), so harvest reports the
        last non-ZeRO refresh (docs/observability.md "Numerics")."""
        from deeplearning4j_tpu.backend.compat import shard_map
        from deeplearning4j_tpu.observability import introspection, numerics
        from deeplearning4j_tpu.resilience import stability

        if type(self)._param_layout is not SyncTrainingMaster._param_layout:
            raise ValueError(
                "update_sharding='zero' composes only with the base "
                "data-parallel param layout (replicated); "
                f"{type(self).__name__} overrides _param_layout")
        cfg = net.conf.updater
        policy = net.conf.stability
        plan = introspection.plan_for(net)
        lr_overrides = {
            l.name: l.learning_rate for l in net.layers
            if l.learning_rate is not None
        }
        mesh = self.mesh
        K = mesh.shape[backend.AXIS_DATA]
        layout = self._zero_layout
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P(backend.AXIS_DATA))
        players = layout.tree_shardings(net.params)
        ulayers: Any = (layout.upd_shardings(net.updater_state)
                        if net.updater_state else repl)
        pmask = layout.mask(net.params)
        p_specs = layout.tree_specs(net.params)
        kw = ({"collect_acts": True}
              if plan is not None and plan.collect_acts else {})
        AX = zero_mod.AXIS

        def step(params, upd_state, net_state, iteration, x, y, rng, fm, lm):
            num_held, upd_state = numerics.split_state(upd_state)
            if plan is not None:
                _, upd_state = introspection.split_state(upd_state)
            if policy is not None:
                stab, inner = stability.split_state(upd_state)
                row_ok = stability.finite_rows(x, y)
                x = stability.zero_nonfinite_rows(x, row_ok)
                y = stability.zero_nonfinite_rows(y, row_ok)
                lm = lm * row_ok.reshape((row_ok.shape[0],)
                                         + (1,) * (lm.ndim - 1))
                scale = stab["loss_scale"]
            else:
                stab, inner = None, upd_state
                scale = jnp.ones((), jnp.float32)
            has_fm = fm is not None

            def local(p_blk, ns, xb, yb, rngb, lmb, sc, *rest):
                fmb = rest[0] if has_fm else None
                p_full = zero_mod.all_gather_tree(p_blk, pmask)
                # this shard's share of the global normalizer: the
                # per-shard loss is sum/max(sum(mask),1) + reg, so
                # weighting it by sum(mask_shard)/psum(sum(mask)) makes
                # the psum of weighted losses the exact global masked
                # mean + reg (a fully-masked shard contributes 0, and
                # the reg term's weights sum to 1)
                denom = jnp.sum(lmb.astype(jnp.float32))
                n_total = lax.psum(denom, AX)
                w = jnp.where(n_total > 0,
                              denom / jnp.maximum(n_total, 1.0), 0.0)

                def weighted_loss(p, n):
                    loss, aux = net._loss_fn(p, n, xb, yb, rngb, fmb, lmb,
                                             None, **kw)
                    return loss * (w * sc), (loss, aux)

                (_, (loss_raw, aux)), g = jax.value_and_grad(
                    weighted_loss, has_aux=True)(p_full, ns)
                new_ns, _, act_stats = introspection.unpack_aux(plan, aux)
                gloss = lax.psum(loss_raw * w, AX)
                g_sh = zero_mod.reduce_scatter_tree(g, K)
                # per-shard batch statistics averaged into the
                # replicated net state (batch-norm caveat:
                # docs/PARALLELISM.md "ZeRO")
                new_ns = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, AX), new_ns)
                if act_stats is not None:
                    act_stats = jax.tree_util.tree_map(
                        lambda a: lax.pmean(a, AX), act_stats)
                    return g_sh, gloss, new_ns, act_stats
                return g_sh, gloss, new_ns

            g_specs = jax.tree_util.tree_map(
                lambda m: P(AX) if m else P(), pmask)
            in_specs = (p_specs, P(), P(AX), P(AX), P(), P(AX), P()) \
                + ((P(AX),) if has_fm else ())
            out_specs = (g_specs, P(), P()) \
                + ((P(),) if kw else ())
            args = (params, net_state, x, y, rng, lm, scale) \
                + ((fm,) if has_fm else ())
            out = shard_map(local, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=False)(*args)
            if kw:
                g_sh, gloss, new_ns, act_stats = out
            else:
                (g_sh, gloss, new_ns), act_stats = out, None
            g_sh = {k: v for k, v in g_sh.items() if v}
            if policy is None:
                updates, new_us = upd.update(cfg, g_sh, inner, iteration,
                                             lr_overrides, params=params)
                new_params = {
                    ln: (upd.apply_updates(params[ln], u)
                         if (u := updates.get(ln)) else params[ln])
                    for ln in params
                }
                introspection.attach(
                    new_us, plan, grads=g_sh, params=params,
                    new_params=new_params, iteration=iteration,
                    act_stats=act_stats)
                if num_held is not None:
                    # stale carry-through (see the docstring)
                    new_us[numerics.STATE_KEY] = num_held
                return new_params, new_us, new_ns, gloss
            # guarded tail on the SHARDED trees: the all-poisoned-batch
            # veto and the device-side skip mask work unchanged (the
            # finiteness reductions over sharded leaves are global)
            new_params, new_us, new_ns, _ = stability.apply_guarded_update(
                policy, cfg, stab, inner, params, net_state, gloss, g_sh,
                new_ns, iteration, lr_overrides,
                extra_ok=jnp.sum(row_ok) > 0)
            introspection.attach(
                new_us, plan, grads=g_sh, params=params,
                new_params=new_params, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"])
            if num_held is not None:
                # stale carry-through (see the docstring)
                new_us[numerics.STATE_KEY] = num_held
            return (new_params, new_us, new_ns, gloss,
                    stability.slot_poison_flags(row_ok, K))

        in_shardings = (players, ulayers, repl, repl, data, data, repl,
                        data, data)
        out_shardings = (players, ulayers, repl, repl)
        if policy is not None:
            out_shardings = out_shardings + (repl,)
        self._step = instrument(jax.jit(
            step,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=(0, 1, 2),
        ), f"{type(self).__name__}.step_zero", argnums=(3, 4, 5, 6, 7, 8))
        self._data_sharding = data
        self._repl_sharding = repl
        self._params_layout = players
        self._upd_layout = ulayers

    def execute_training(self, net, iterator):
        from deeplearning4j_tpu.datasets.iterator import AsyncDataSetIterator, DataSetIterator
        from deeplearning4j_tpu.models.common import notify_listeners
        from deeplearning4j_tpu.resilience import (
            FitResilience, get_fault_injector, preemption_requested,
        )

        res = None
        if self.checkpoint_manager is not None or self.retry_policy is not None:
            # resume BEFORE device placement so restored leaves get their
            # saved PartitionSpecs over this master's mesh
            res = FitResilience("sync_master", self.checkpoint_manager,
                                self.retry_policy, net=net, mesh=self.mesh)
        if isinstance(iterator, DataSetIterator) and iterator.async_supported():
            iterator = AsyncDataSetIterator(iterator, self.prefetch_size)
        policy = net.conf.stability
        if policy is not None:
            from deeplearning4j_tpu.resilience import stability

            # stability state must exist BEFORE device placement so the
            # guard/scale scalars ride in upd_state under _upd_layout
            stability.ensure_state(net)
            created = self._stab_rt is None
            if created:
                slots = self._data_slot_devices()
                self._stab_workers = [f"d{s[0].id}" for s in slots]
                self._stab_rt = stability.StabilityRuntime(
                    "sync_master", policy, worker_ids=self._stab_workers)
            if created or (res is not None and res.resumed_from is not None):
                # a restored nonfinite_total is history, not fresh evidence
                self._stab_rt.baseline_from(
                    net.updater_state.get(stability.STATE_KEY))
        stab_rt = self._stab_rt
        introspect = getattr(net.conf, "introspection", None) is not None
        if introspect:
            from deeplearning4j_tpu.observability import introspection

            # introspection state must exist BEFORE _build/device
            # placement so the stat vectors ride in upd_state (replicated
            # under _upd_layout)
            introspection.ensure_state(net)
        numerics_on = getattr(net.conf, "numerics", None) is not None
        if numerics_on:
            from deeplearning4j_tpu.observability import numerics

            # precision-ledger state likewise rides replicated
            numerics.ensure_state(net)
        if self._step is None:
            if self.update_sharding == zero_mod.ZERO:
                self._build_zero(net)
            else:
                self._build(net)
        params = jax.device_put(net.params, self._params_layout)
        upd_state = jax.device_put(net.updater_state, self._upd_layout)
        ns = jax.device_put(net.net_state, self._repl_sharding)
        K = self.mesh.shape[backend.AXIS_DATA]
        # sharding ledger under the master's actual layouts: replicated
        # params/updater read factor = mesh size — the measured baseline
        # the ZeRO update sharding (ROADMAP item 2) regresses against.
        # Metadata walk only, before the first (donating) dispatch.
        # Component matches the rest of this loop's telemetry (step_guard
        # and PhaseStats label "sync_master" for subclasses too, so the
        # ledger stays joinable with the step metrics).
        shardstats.record_ledger(
            "sync_master",
            {"params": params, "updater_state": upd_state, "net_state": ns},
            data_axis_size=K,
            notes=(self._zero_layout.notes()
                   if self._zero_layout is not None else None))
        it = iter(iterator)
        while True:
            # phases ≙ CommonSparkTrainingStats: fetch (split/repartition),
            # place (broadcast), dispatch (mapPartitions fit; the gradient
            # all-reduce — the reference's aggregate — is inside the program)
            with self._phases.phase("fetch"):
                try:
                    ds = next(it)
                except StopIteration:
                    break
            if res is not None and res.skip_batch():
                continue   # auto-resume: batch already covered by the ckpt
            if preemption_requested():
                # fold live state back so the priority checkpoint sees it
                net.params, net.updater_state, net.net_state = (
                    params, upd_state, ns)
                if res is not None:
                    res.on_preempt(net)
                break
            n_real = len(ds)
            if len(ds) % K:
                ds = ds.pad_batch(((len(ds) + K - 1) // K) * K)
            emask = None
            step0 = net.iteration   # pre-advance: barrier polls the SAME
            if self._elastic is not None:   # step begin_window decided on
                emask = self._elastic.begin_window(step0)
                if emask.min() >= 1.0:
                    emask = None    # healthy mesh: untouched fast path
            feats = ds.features
            inj = get_fault_injector()
            if inj is not None and inj.has_poison():
                # deterministic chaos: data slot k owns the contiguous
                # row block [k*B/K, (k+1)*B/K) of the global batch
                # (poison flows regardless of the guard — the unguarded
                # arm is the bench/test contrast)
                if not self._stab_workers:
                    self._stab_workers = [
                        f"d{s[0].id}" for s in self._data_slot_devices()]
                # poison_rows copies host-side only when a rule matches
                feats = inj.poison_rows(self._stab_workers, step0, feats, K)
            t0 = time.perf_counter()
            with self._phases.phase("place"):
                x = jax.device_put(jnp.asarray(feats), self._data_sharding)
                y = jax.device_put(jnp.asarray(ds.labels), self._data_sharding)
                fm = None if ds.features_mask is None else jax.device_put(
                    jnp.asarray(ds.features_mask), self._data_sharding)
                if (self._elastic is None and stab_rt is None
                        and self.update_sharding != zero_mod.ZERO):
                    lm_host = ds.labels_mask
                elif emask is not None:
                    lm_host = self._evicted_labels_mask(ds, emask, K)
                elif ds.labels_mask is not None:
                    lm_host = ds.labels_mask
                else:
                    # elasticity/stability/ZeRO keep ONE trace: the mask
                    # argument is always an array (all-ones == the
                    # unmasked mean; the ZeRO step also reads the
                    # per-shard mask sums as its loss weights), so the
                    # first eviction or poisoned row flips values, not
                    # the pytree — no recompile at the moment the mesh
                    # degrades
                    lm_host = np.ones(
                        (len(ds),) + (1,) * (ds.labels.ndim - 2),
                        np.float32)
                lm = None if lm_host is None else jax.device_put(
                    jnp.asarray(lm_host), self._data_sharding)
            with step_guard("sync_step", component="sync_master",
                            iteration=net.iteration):
                with self._phases.phase("dispatch"):
                    if res is not None:
                        out = res.step(
                            lambda: self._step(
                                params, upd_state, ns,
                                jnp.asarray(float(net.iteration)),
                                x, y, net._keys.next(), fm, lm),
                            net.iteration, net=net)
                    else:
                        out = self._step(
                            params, upd_state, ns,
                            jnp.asarray(float(net.iteration)),
                            x, y, net._keys.next(), fm, lm,
                        )
                    if stab_rt is not None:
                        params, upd_state, ns, loss, slot_poison = out
                        # device-side add only; read at check boundaries
                        stab_rt.accumulate(poison_flags=slot_poison)
                    else:
                        params, upd_state, ns, loss = out
            if introspect:
                # live device reference for listeners (the facade's
                # updater_state is stale until the loop exits); no
                # transfer until a reporting interval reads it
                net._introspect_live = upd_state[introspection.STATE_KEY]
            if numerics_on:
                from deeplearning4j_tpu.observability import numerics

                net._numerics_live = upd_state[numerics.STATE_KEY]
            net.score_value = loss  # device scalar; fetched lazily on read
            net.iteration += 1
            if stab_rt is not None:
                from deeplearning4j_tpu.resilience import stability

                action = stab_rt.poll_master(
                    step=net.iteration, losses=loss,
                    stab_state=upd_state[stability.STATE_KEY],
                    elastic=self._elastic,
                    can_rewind=res is not None and res.cm is not None)
                if action == "backoff":
                    upd_state = stability.apply_lr_backoff_tree(
                        upd_state, policy)
                elif action == "rewind":
                    net.params, net.updater_state, net.net_state = (
                        params, upd_state, ns)
                    if stab_rt.rewind(net, res.cm, mesh=self.mesh) is not None:
                        # restage the rewound facade state onto the mesh
                        params = jax.device_put(net.params,
                                                self._params_layout)
                        upd_state = jax.device_put(net.updater_state,
                                                   self._upd_layout)
                        ns = jax.device_put(net.net_state,
                                            self._repl_sharding)
            if res is not None and res.cm is not None:
                trigger = res.cm.due(net.iteration)
                if trigger is not None:
                    # fold live state into the facade only when a save is
                    # actually due (the snapshot reads net.*)
                    net.params, net.updater_state, net.net_state = (
                        params, upd_state, ns)
                    res.cm.save(net, trigger=trigger)
            if self.collect_stats:
                if self._workers is None:
                    if self._elastic is not None:
                        self._workers = (
                            self._elastic.cfg.make_worker_telemetry(
                                "sync_master"))
                    else:
                        self._workers = WorkerTelemetry("sync_master")
                    if self._elastic is not None:
                        self._elastic.attach_detector(self._workers.detector)
                with self._phases.phase("device_sync"):
                    worker_times = self._measure_worker_sync(loss, t0)
                step_s = time.perf_counter() - t0
                self._stats["step_time_ms"].append(step_s * 1e3)
                per_dev = max(1, len(ds) // K)
                inj = get_fault_injector()
                for worker, w_s in (worker_times
                                    or {str(i): step_s
                                        for i in range(K)}).items():
                    if inj is not None:
                        w_s += inj.worker_delay(worker)
                    self._workers.observe(worker, w_s, batch=per_dev)
            if self._elastic is not None:
                # synchrony-barrier simulation (fault injection only):
                # lockstep pays the slowest ACTIVE worker's delay per step
                self._elastic.window_barrier(step0)
            self._stats["steps"] += 1
            self._phases.steps += 1
            if net.listeners:
                # listeners read model.params/updater_state; the facade's
                # stale references point at buffers the jitted step
                # DONATED — rebind to the live step outputs (reference
                # assignment only, no copy; the loop-exit fold-back does
                # exactly this)
                net.params, net.updater_state, net.net_state = (
                    params, upd_state, ns)
            notify_listeners(net, n_real)
        net.params, net.updater_state, net.net_state = params, upd_state, ns
        if stab_rt is not None:
            stab_rt.flush(net)   # tail past the last check boundary

    def _measure_worker_sync(self, loss, t_step0: float) -> Dict[str, float]:
        """Device-sync on the step result, measuring each device's shard
        arrival relative to the host step start.  Blocking the shards in
        turn completes no later than the single ``block_until_ready`` it
        replaces.

        Measurement honesty: the loss is the all-reduced replicated
        scalar, and the collective gates every device on the slowest one
        — so the per-device times here share the cluster critical path
        rather than attributing blame (post-collective skew, e.g. the
        updater apply, is the visible part).  They give the registry an
        accurate per-step cluster distribution; real per-worker
        attribution arrives via ``WorkerTelemetry.observe`` from
        per-host timing in multi-process deployments (this method is the
        in-process seam)."""
        times: Dict[str, float] = {}
        try:
            shards = list(loss.addressable_shards)
        except Exception:
            shards = []
        for sh in shards:
            try:
                jax.block_until_ready(sh.data)
            except Exception:
                continue
            times[f"d{sh.device.id}"] = time.perf_counter() - t_step0
        jax.block_until_ready(loss)
        return times

    def training_stats(self):
        out = dict(self._stats)
        out["step_time_ms"] = list(out["step_time_ms"])  # JSON-safe snapshot
        out.update(self._phases.as_dict())
        if self._workers is not None:
            out["cluster"] = self._workers.cluster_view()
        if self._elastic is not None:
            out["elastic"] = self._elastic.summary()
        return out


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Reference-semantics parameter averaging over the mesh.

    ``workers`` replicas each train ``averaging_frequency`` minibatches
    locally (zero communication — vmapped replicas), then parameters (and
    optionally updater state) are averaged: the reference's
    broadcast→train→aggregate cycle collapsed into one XLA program where
    "aggregate" is an ICI all-reduce instead of a driver tree-reduce.
    """

    def __init__(self, workers: Optional[int] = None, batch_size: int = 32,
                 averaging_frequency: int = 5, average_updaters: bool = True,
                 prefetch_size: int = 2, repartition: str = "always",
                 mesh: Optional[Mesh] = None, collect_stats: bool = False,
                 elastic=False, update_sharding: str = zero_mod.REPLICATED):
        self.mesh = mesh or backend.default_mesh()
        self.workers = workers or self.mesh.shape[backend.AXIS_DATA]
        self.batch_size = batch_size
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.prefetch_size = prefetch_size
        self.collect_stats = collect_stats
        # forwarded to each per-fit ParallelWrapper; validated HERE so a
        # bad mode (or ZeRO with a local-SGD frequency) fails at
        # construction like the other masters, not at the first fit
        self.update_sharding = zero_mod.validate_mode(update_sharding,
                                                      self.mesh)
        if (self.update_sharding == zero_mod.ZERO
                and self.averaging_frequency != 1):
            raise ValueError(
                "update_sharding='zero' requires averaging_frequency=1 "
                f"(got {self.averaging_frequency}): local-SGD windows "
                "need full per-replica updater state between averages")
        # One persistent controller shared by every per-fit ParallelWrapper:
        # eviction state and flag budgets survive epoch boundaries instead
        # of resetting with each epoch's fresh wrapper.
        self._elastic: Optional[ElasticController] = None
        if elastic is not False and elastic is not None:
            ecfg = (elastic if isinstance(elastic, ElasticConfig)
                    else ElasticConfig())
            self._elastic = ElasticController(
                "parallel_wrapper", [str(k) for k in range(self.workers)],
                config=ecfg)
        self._stats: Dict[str, Any] = {"windows": 0}
        self._phases = PhaseStats(component="param_avg_master")

    @property
    def elastic(self) -> Optional[ElasticController]:
        """The elasticity state machine (None unless ``elastic=`` was
        passed) — ``elastic.summary()`` is the operator view."""
        return self._elastic

    def execute_training(self, net, iterator):
        from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

        pw = ParallelWrapper(
            net,
            workers=self.workers,
            prefetch_size=self.prefetch_size,
            averaging_frequency=self.averaging_frequency,
            average_updaters=self.average_updaters,
            mesh=self.mesh,
            elastic=self._elastic if self._elastic is not None else False,
            update_sharding=self.update_sharding,
        )
        with self._phases.phase("fit"):
            pw.fit(iterator)
        self._stats["windows"] += 1
        self._phases.steps += pw.iteration  # accumulate across epochs

    def training_stats(self):
        out = dict(self._stats)
        out.update(self._phases.as_dict())
        if self._elastic is not None:
            out["elastic"] = self._elastic.summary()
        return out


class DistributedNetwork:
    """Facade pairing a network with a TrainingMaster (reference
    ``SparkDl4jMultiLayer.java:72``: wraps net + master, fit(RDD)).
    Evaluation shards the eval batch over the mesh the same way."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.master = training_master

    def fit(self, iterator, epochs: int = 1):
        try:
            for _ in range(epochs):
                self.master.execute_training(self.net, iterator)
        except Exception as e:
            # leave the same diagnosis artifact a hang would (flight
            # events + live spans + registry), then re-raise
            crash_dump("fit_exception",
                       master=type(self.master).__name__, error=repr(e))
            raise
        return self.net

    def evaluate(self, iterator, evaluation=None):
        """Evaluation with the forward pass sharded over the master's mesh
        (≙ Spark evaluation as mapPartitions + tree-aggregated counts: each
        device scores its batch shard, metrics accumulate on host)."""
        from deeplearning4j_tpu.evaluation import Evaluation

        ev = evaluation or Evaluation()
        mesh = getattr(self.master, "mesh", None)
        out_fn = self.net.output
        pad_to = 1
        # sharded fast path needs the net's cached jittable forward
        # (MultiLayerNetwork); ComputationGraph falls back to net.output
        if (mesh is not None and backend.AXIS_DATA in mesh.shape
                and hasattr(self.net, "_output_fn")):
            pad_to = mesh.shape[backend.AXIS_DATA]
            if getattr(self, "_eval_mesh", None) is not mesh:
                data = NamedSharding(mesh, P(backend.AXIS_DATA))
                # params/net-state shardings are taken from the ARGS
                # (None = as-given): after a ZeRO fit the facade holds
                # genuinely sharded params, and pinning them replicated
                # here would reject them — GSPMD gathers what the
                # forward needs either way
                self._eval_fn = jax.jit(self.net._output_fn(),
                                        in_shardings=(None, None, data,
                                                      data))
                self._eval_mesh = mesh
            sharded = self._eval_fn

            def out_fn(x, fmask=None):  # noqa: E306
                return sharded(self.net.params, self.net.net_state,
                               jnp.asarray(x),
                               None if fmask is None else jnp.asarray(fmask))

        for ds in iterator:
            n = len(ds)
            if n % pad_to:
                ds_run = ds.pad_batch(((n + pad_to - 1) // pad_to) * pad_to)
            else:
                ds_run = ds
            out = np.asarray(out_fn(ds_run.features,
                                    fmask=ds_run.features_mask))[:n]
            ev.eval(ds.labels, out, mask=ds.labels_mask)
        return ev

    def score(self, dataset):
        return self.net.score(dataset.features, dataset.labels)

    def training_stats(self):
        return self.master.training_stats()
