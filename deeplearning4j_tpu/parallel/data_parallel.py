"""Single-host data parallelism — the ParallelWrapper redesign.

Reference: ``deeplearning4j-core/.../parallelism/ParallelWrapper.java:37-205``:
N Java threads each own a model replica pinned to a device; a round-robin
queue feeds them; every ``averagingFrequency`` iterations params are averaged
via ``Nd4j.averageAndPropagate`` (and optionally updater state too).

TPU-native redesign: no threads, no queues, no host-side averaging.  The K
replicas are ONE jitted program over a ``Mesh``:

- replica params are a stacked pytree (leading axis K) sharded over the
  'data' mesh axis — each device holds exactly its replica;
- the per-replica train step is ``jax.vmap`` of the single-model step, so
  the whole "N workers train independently" phase is a single XLA program
  with zero communication;
- parameter averaging is ``mean over the replica axis`` — XLA lowers it to
  an all-reduce that rides ICI (replacing averageAndPropagate), followed by
  re-broadcast.  Updater-state averaging is the same tree-map, gated by
  ``average_updaters`` exactly like the reference.

``averaging_frequency=1`` + SGD reproduces synchronous DP; higher
frequencies reproduce the reference's looser local-SGD semantics bit-for-bit
(see tests/test_parallel.py equivalence tests).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.observability import (
    PhaseTimers, WorkerTelemetry, get_registry, instrument, step_guard,
)
from deeplearning4j_tpu.observability import shardstats
from deeplearning4j_tpu.optimize import updaters as upd
from deeplearning4j_tpu.parallel import zero as zero_mod
from deeplearning4j_tpu.parallel.elastic import ElasticConfig, ElasticController


def _stack_tree(tree, k: int):
    return jax.tree_util.tree_map(lambda a: jnp.broadcast_to(a[None], (k,) + a.shape), tree)


_SENTINEL = object()


class _WindowAssembler:
    """Background window assembly: a producer thread groups minibatches into
    stacked [F, K, B, ...] host arrays so padding/stacking overlaps device
    execution of the previous window (the native-ETL principle applied to
    the DP hot path; producer errors re-raise on the consumer side; an
    abandoned consumer unblocks the producer via the stop event)."""

    def __init__(self, iterator, K: int, F: int, stack_fn, prefetch: int = 2):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()

        def put(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._queue.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def run():
            try:
                window = []
                for ds in iterator:
                    window.append(ds)
                    if len(window) == K * F:
                        if not put(stack_fn(window, K * F)):
                            return
                        window = []
                if window and not self._stop.is_set():
                    # tail handling: emit the full frames as their own
                    # window first — a whole-tail per-replica weight would
                    # also discard those replicas' REAL earlier minibatches
                    n_full = (len(window) // K) * K
                    if n_full and not put(stack_fn(window[:n_full], n_full)):
                        return
                    window = window[n_full:]
                if window and not self._stop.is_set():
                    # partial final frame: duplicate the tail minibatch to
                    # fill the K replica slots (keeps a compiled [1, K, ...]
                    # shape); n_real lets the stacker weight the pad-filled
                    # replicas out of the average (they'd double-count the
                    # duplicate)
                    n_real = len(window)
                    while len(window) % K:
                        window.append(window[-1])
                    put(stack_fn(window, n_real))
            except BaseException as e:
                self._error = e
            finally:
                put(_SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def close(self):
        self._stop.set()
        try:  # unblock a producer waiting on a full queue
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            # bounded join: the drain above freed the queue, so the
            # producer reaches its sentinel promptly — and a re-iteration
            # never races a half-dead assembler on the same queue
            self._thread.join(timeout=5.0)
            if not self._thread.is_alive():
                self._thread = None

    def __iter__(self):
        try:
            while True:
                item = self._queue.get()
                if item is _SENTINEL:
                    if self._error is not None:
                        err, self._error = self._error, None
                        raise RuntimeError("window assembly failed") from err
                    return
                yield item
        finally:
            self.close()


class ParallelWrapper:
    """Data-parallel trainer over the local mesh.

    Usage mirrors the reference builder:
        pw = ParallelWrapper(net, workers=8, prefetch_size=2,
                             averaging_frequency=3, average_updaters=True)
        pw.fit(iterator)
    """

    def __init__(
        self,
        net,
        workers: Optional[int] = None,
        prefetch_size: int = 2,
        averaging_frequency: int = 1,
        average_updaters: bool = True,
        mesh: Optional[Mesh] = None,
        collect_worker_stats: bool = False,
        checkpoint_manager=None,
        retry_policy=None,
        elastic=False,
        update_sharding: str = zero_mod.REPLICATED,
    ):
        self.net = net
        # resilience wiring (docs/resilience.md): auto-resume on fit entry,
        # window-boundary saves, clean preemption stop, transient retry
        self.checkpoint_manager = checkpoint_manager
        self.retry_policy = retry_policy
        self.mesh = mesh or backend.default_mesh()
        self.workers = workers or self.mesh.shape[backend.AXIS_DATA]
        if self.workers != self.mesh.shape[backend.AXIS_DATA]:
            raise ValueError(
                f"workers={self.workers} must equal the mesh data-axis size "
                f"{self.mesh.shape[backend.AXIS_DATA]}"
            )
        self.prefetch_size = prefetch_size
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updaters = average_updaters
        self._step_fn = None
        self.iteration = 0
        # wait≙time blocked on window assembly (host ETL), dispatch≙the
        # vmapped train window + averaging all-reduce
        self._phases = PhaseTimers("parallel_wrapper")
        # per-replica step time + throughput -> labeled registry families
        # + straggler detection (SparkNet/DeepSpark: the run goes at the
        # slowest replica's speed).  OPT-IN because the measurement costs
        # one device sync per window, which breaks the default loop's
        # async overlap of host window-assembly with device execution
        # (same gating as SyncTrainingMaster's collect_stats).
        self.collect_worker_stats = collect_worker_stats
        self._workers: Optional[WorkerTelemetry] = None
        # elasticity (docs/resilience.md "Elasticity"): evict a straggling,
        # hung, or dead replica from the averaging collective via a runtime
        # [K] weight mask (no recompile), renormalize over the healthy set,
        # re-admit at a window boundary after the fault clears.  Pass True
        # or an ElasticConfig; requires worker stats for straggler verdicts.
        # An existing ElasticController is adopted as-is so eviction state
        # can outlive one wrapper (ParameterAveragingTrainingMaster builds
        # a fresh wrapper per epoch around one persistent controller).
        self._elastic: Optional[ElasticController] = None
        self._ones_w: Optional[np.ndarray] = None
        self._stab_rt = None   # StabilityRuntime (net.conf.stability)
        # ZeRO update sharding (arXiv 2004.13336, docs/PARALLELISM.md
        # "ZeRO"): persistent params + updater state live sharded 1/K
        # per device; each window all-gathers the params, computes
        # per-replica gradients, moves every replica's gradient shard to
        # its owner (an all-to-all — the wrapper's averaging semantics
        # need each replica's OWN gradient because the per-replica Adam
        # updates it averages are nonlinear in them; same wire bytes as
        # a reduce-scatter), and applies the weighted-average update to
        # the local shard.  Restricted to averaging_frequency=1 +
        # average_updaters=True: higher frequencies are local SGD, where
        # every replica needs its own full moments between averages —
        # there is nothing shardable.
        self.update_sharding = zero_mod.validate_mode(update_sharding,
                                                      self.mesh)
        self._zero_layout: Optional[zero_mod.ZeroLayout] = None
        if self.update_sharding == zero_mod.ZERO:
            if self.averaging_frequency != 1:
                raise ValueError(
                    "update_sharding='zero' requires averaging_frequency"
                    f"=1 (got {self.averaging_frequency}): local-SGD "
                    "windows need full per-replica updater state between "
                    "averages")
            if not self.average_updaters:
                raise ValueError(
                    "update_sharding='zero' requires average_updaters="
                    "True: un-averaged updater state is per-replica and "
                    "cannot be sharded")
            self._zero_layout = zero_mod.ZeroLayout(self.mesh, self.workers)
        if isinstance(elastic, ElasticController):
            if elastic.K != self.workers:
                raise ValueError(
                    f"elastic controller tracks {elastic.K} workers, "
                    f"wrapper has {self.workers}")
            self.collect_worker_stats = True
            self._elastic = elastic
        elif elastic is not False and elastic is not None:
            cfg = elastic if isinstance(elastic, ElasticConfig) else ElasticConfig()
            self.collect_worker_stats = True
            self._elastic = ElasticController(
                "parallel_wrapper", [str(k) for k in range(self.workers)],
                config=cfg)

    @property
    def elastic(self) -> Optional[ElasticController]:
        """The elasticity state machine (None unless ``elastic=`` was
        passed) — ``elastic.summary()`` is the operator view."""
        return self._elastic

    # -- sharding specs ----------------------------------------------------
    def _replica_sharding(self):
        """Leading replica axis sharded over 'data'; inner dims replicated."""
        return NamedSharding(self.mesh, P(backend.AXIS_DATA))

    def _build(self):
        if self.update_sharding == zero_mod.ZERO:
            return self._build_zero()
        from deeplearning4j_tpu.observability import introspection, numerics

        net = self.net
        cfg = net.conf.updater
        policy = net.conf.stability
        plan = introspection.plan_for(net)
        nplan = numerics.plan_for(net)
        lr_overrides = {
            l.name: l.learning_rate for l in net.layers if l.learning_rate is not None
        }
        avg_freq = self.averaging_frequency
        average_updaters = self.average_updaters

        def one_replica_step(params, upd_state, net_state, iteration, x, y, rng, fm, lm):
            nstate = None
            if nplan is not None:
                nstate, upd_state = numerics.split_state(upd_state)
            if plan is not None:
                _, upd_state = introspection.split_state(upd_state)
            # iteration is unmapped under the vmap, so this predicate
            # stays a true lax.cond per replica (not a select)
            now = numerics.collect_now(nplan, iteration)
            kw = ({"collect_acts": True}
                  if numerics.wants_acts(plan, nplan) else {})
            if kw and now is not None:
                kw["numerics_now"] = now
            if policy is None:
                (loss, aux), grads = jax.value_and_grad(net._loss_fn, has_aux=True)(
                    params, net_state, x, y, rng, fm, lm, None, **kw
                )
                new_ns, _, act_stats = numerics.unpack_aux(plan, nplan, aux)
                grads = {k: v for k, v in grads.items() if v}
                updates, new_us = upd.update(cfg, grads, upd_state, iteration,
                                             lr_overrides, params=params)
                new_params = dict(params)
                for lname, u in updates.items():
                    new_params[lname] = upd.apply_updates(params[lname], u)
                # vmapped: each replica refreshes its own [L] slice, so
                # the window exits with a [K, L] per-replica view
                introspection.attach(
                    new_us, plan, grads=grads, params=params,
                    new_params=new_params, iteration=iteration,
                    act_stats=act_stats)
                numerics.attach(
                    new_us, nplan, grads=grads, iteration=iteration,
                    act_stats=act_stats, prev=nstate, now=now)
                return new_params, new_us, new_ns, loss, jnp.ones(())
            # non-finite step guard per replica (resilience/stability.py):
            # a poisoned replica's step is a device-side no-op; the window
            # averaging below ALSO weights it out of the collective
            from deeplearning4j_tpu.resilience import stability

            stab, inner = stability.split_state(upd_state)
            (_, (loss, aux)), grads = jax.value_and_grad(
                stability.scaled_loss(net._loss_fn, stab), has_aux=True)(
                params, net_state, x, y, rng, fm, lm, None, **kw)
            new_ns, _, act_stats = numerics.unpack_aux(plan, nplan, aux)
            new_params, new_us, new_ns, finite = (
                stability.apply_guarded_update(
                    policy, cfg, stab, inner, params, net_state,
                    loss, grads, new_ns, iteration, lr_overrides))
            introspection.attach(
                new_us, plan, grads=grads, params=params,
                new_params=new_params, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"])
            numerics.attach(
                new_us, nplan, grads=grads, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"],
                prev=nstate, now=now)
            return new_params, new_us, new_ns, loss, finite.astype(jnp.float32)

        vstep = jax.vmap(one_replica_step, in_axes=(0, 0, 0, None, 0, 0, 0, 0, 0))

        def fit_window(params_k, upd_k, ns_k, iteration, xs, ys, rngs, fms, lms,
                       weights):
            """avg_freq minibatches per replica, then average.
            xs: [avg_freq, K, B, ...]; weights: [K] replica weights — 0 for
            evicted replicas (degraded mode) and pad-filled tail replicas,
            1 otherwise.  The average is renormalized over the weighted
            set and broadcast into ALL K slots, so an evicted replica's
            slot always holds the current healthy average (that broadcast
            IS the re-admission catch-up).  With the stability engine on,
            a replica with ANY non-finite step this window is additionally
            weighted out (poison masking — same zero-recompile mask), and
            the window reports [K] poison flags + a non-finite step count."""

            def body(carry, inp):
                p, u, n, it = carry
                x, y, rng, fm, lm = inp
                p, u, n, loss, fin = vstep(p, u, n, it, x, y, rng, fm, lm)
                return (p, u, n, it + 1.0), (loss, fin)

            (params_k, upd_k, ns_k, _), (losses, finites) = jax.lax.scan(
                body, (params_k, upd_k, ns_k, iteration), (xs, ys, rngs, fms, lms)
            )
            if policy is not None:
                # [K] 1 where every step of the window was finite
                win_finite = jnp.min(finites, axis=0)
                w_eff = weights * win_finite
                # all real replicas poisoned: fall back to the original
                # weights — every per-replica update was already skipped
                # device-side, so the average stays finite either way
                safe = jnp.sum(w_eff) > 0
                weights = jnp.where(safe, w_eff, weights)
            # parameter averaging: weighted all-reduce over the replica
            # axis then re-broadcast (reference averageAndPropagate
            # semantics, renormalized over the healthy/unpadded set —
            # sum(w)=K with all weights 1 reproduces the plain mean
            # bit-for-bit ... the caller guarantees sum(w) > 0)
            wsum = jnp.sum(weights)

            def wavg(a):
                w = weights.reshape((a.shape[0],) + (1,) * (a.ndim - 1))
                m = jnp.sum(a * w, 0, keepdims=True) / wsum
                return jnp.broadcast_to(m.astype(a.dtype), a.shape)

            params_k = jax.tree_util.tree_map(wavg, params_k)
            ns_k = jax.tree_util.tree_map(wavg, ns_k)
            if average_updaters:
                # the introspection and numerics subtrees are PER-REPLICA
                # views — averaging them would erase exactly the
                # per-replica divergence signal they exist to expose
                held = {k: upd_k[k]
                        for k in (introspection.STATE_KEY, numerics.STATE_KEY)
                        if k in upd_k}
                if held:
                    rest = {k: v for k, v in upd_k.items() if k not in held}
                    rest = jax.tree_util.tree_map(wavg, rest)
                    rest.update(held)
                    upd_k = rest
                else:
                    upd_k = jax.tree_util.tree_map(wavg, upd_k)
            if policy is not None:
                return (params_k, upd_k, ns_k, losses,
                        1.0 - win_finite, jnp.sum(1.0 - finites))
            return params_k, upd_k, ns_k, losses

        self._step_fn = instrument(
            jax.jit(fit_window, donate_argnums=(0, 1, 2)),
            "ParallelWrapper.fit_window", argnums=(3, 4, 5, 6, 7, 8, 9))

    def _build_zero(self):
        """The ZeRO-sharded window (update_sharding="zero",
        averaging_frequency=1): persistent params + optimizer moments
        live sharded 1/K per device.  Inside a ``shard_map`` each device
        all-gathers the params, runs ITS replica's forward/backward
        (same per-replica RNG keys and per-layer gradient normalization
        as the vmapped replicated window), and an all-to-all hands every
        replica's gradient shard to its owner.  Outside, under GSPMD,
        the per-replica elementwise updates are computed against the
        SHARED sharded moments, weighted-averaged over replicas (the
        elastic / pad / poison ``[K]`` weight mask applies unchanged),
        and applied to the local shard — reproducing the replicated
        window's average-of-per-replica-updates semantics exactly.  The
        ``__stability__`` / ``__introspect__`` subtrees stay stacked per
        replica as in replicated mode (recorded in the ledger notes).
        The ``__numerics__`` precision-ledger subtree is carried through
        UNCHANGED (stale) — ZeRO's sharded update has no per-replica
        gradient view to measure; harvest reports whatever the last
        non-ZeRO refresh wrote (docs/observability.md "Numerics")."""
        from deeplearning4j_tpu.backend.compat import shard_map
        from deeplearning4j_tpu.observability import introspection, numerics
        from deeplearning4j_tpu.resilience import stability

        net = self.net
        cfg = net.conf.updater
        cfg_sharded = zero_mod.no_norm(cfg)
        policy = net.conf.stability
        plan = introspection.plan_for(net)
        lr_overrides = {
            l.name: l.learning_rate for l in net.layers
            if l.learning_rate is not None
        }
        K = self.workers
        mesh = self.mesh
        layout = self._zero_layout
        pmask = layout.mask(net.params)
        p_specs = layout.tree_specs(net.params)
        kw = ({"collect_acts": True}
              if plan is not None and plan.collect_acts else {})
        AX = zero_mod.AXIS

        def fit_window(p_sh, upd_k, ns_k, iteration, xs, ys, rngs, fms, lms,
                       weights):
            num_k, upd_k = numerics.split_state(upd_k)
            _, upd2 = introspection.split_state(upd_k)
            if policy is not None:
                stab_k, inner_sh = stability.split_state(upd2)
            else:
                stab_k, inner_sh = None, upd2
            # F == 1 enforced at construction: one frame per window
            x1, y1, rng1 = xs[0], ys[0], rngs[0]
            fm1 = None if fms is None else fms[0]
            lm1 = None if lms is None else lms[0]
            has_fm, has_lm = fm1 is not None, lm1 is not None

            def local(p_blk, ns_blk, xk, yk, rngk, *rest):
                i = 0
                fmk = rest[i][0] if has_fm else None
                i += 1 if has_fm else 0
                lmk = rest[i][0] if has_lm else None
                i += 1 if has_lm else 0
                scale = (jax.tree_util.tree_map(lambda a: a[0], rest[i])
                         ["loss_scale"] if policy is not None else None)
                p_full = zero_mod.all_gather_tree(p_blk, pmask)
                ns_local = jax.tree_util.tree_map(lambda a: a[0], ns_blk)
                xk0, yk0, rngk0 = xk[0], yk[0], rngk[0]

                def lf(p, n):
                    loss, aux = net._loss_fn(p, n, xk0, yk0, rngk0, fmk,
                                             lmk, None, **kw)
                    if policy is not None:
                        return loss * scale, (loss, aux)
                    return loss, (loss, aux)

                (_, (loss, aux)), g = jax.value_and_grad(
                    lf, has_aux=True)(p_full, ns_local)
                new_ns, _, act_stats = introspection.unpack_aux(plan, aux)
                if policy is not None:
                    inv = 1.0 / scale
                    g = jax.tree_util.tree_map(lambda a: a * inv, g)
                    finite = stability.all_finite(loss, g)
                else:
                    finite = jnp.ones((), jnp.bool_)
                outs = []
                if plan is not None:
                    # per-replica per-layer grad norms, measured like
                    # replicated mode: raw (unnormalized) unscaled grads
                    outs.append(zero_mod.tree_norms(plan, g)[None])
                # per-replica per-layer normalization on the FULL
                # gradient (exact replicated semantics), BEFORE the
                # scatter — the sharded updater runs with norm off
                g = upd.normalize_tree(cfg, g)
                g_all = zero_mod.all_to_all_tree(g, K)
                head = [g_all, loss[None], finite[None],
                        jax.tree_util.tree_map(lambda a: a[None], new_ns)]
                if act_stats is not None:
                    outs.append(jax.tree_util.tree_map(
                        lambda a: a[None], act_stats))
                return tuple(head + outs)

            in_specs = [p_specs, P(AX), P(AX), P(AX), P(AX)]
            args = [p_sh, ns_k, x1, y1, rng1]
            if has_fm:
                in_specs.append(P(AX)); args.append(fm1)
            if has_lm:
                in_specs.append(P(AX)); args.append(lm1)
            if policy is not None:
                in_specs.append(P(AX)); args.append(stab_k)
            out_specs = [zero_mod.grad_stack_specs(net.params, K),
                         P(AX), P(AX), P(AX)]
            if plan is not None:
                out_specs.append(P(AX))
            if kw:
                out_specs.append(P(AX))
            out = shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                            out_specs=tuple(out_specs),
                            check_vma=False)(*args)
            g_all, losses_k, fin_k, new_ns_k = out[0], out[1], out[2], out[3]
            idx = 4
            gn_k = act_k = None
            if plan is not None:
                gn_k = out[idx]; idx += 1
            if kw:
                act_k = out[idx]
            g_all = {ln: lg for ln, lg in g_all.items() if lg}
            fin_f = fin_k.astype(jnp.float32)
            weights_eff = weights
            if policy is not None:
                # poison masking: a replica with a non-finite step is
                # weighted out; all real replicas poisoned falls back to
                # the original weights (each update is zeroed anyway)
                w_eff = weights * fin_f
                safe = jnp.sum(w_eff) > 0
                weights_eff = jnp.where(safe, w_eff, weights)
            wsum = jnp.sum(weights_eff)

            def rk(vec, a):
                return vec.reshape((a.shape[0],) + (1,) * (a.ndim - 1))

            def wavg_k(a):          # [K, ...] -> [...] weighted mean
                return jnp.sum(a * rk(weights_eff, a), 0) / wsum

            def wavg_bcast(a):      # [K, ...] -> all K slots = the mean
                m = jnp.sum(a * rk(weights_eff, a), 0,
                            keepdims=True) / wsum
                return jnp.broadcast_to(m.astype(a.dtype), a.shape)

            # per-replica elementwise updates against the SHARED sharded
            # moments — the all-to-all delivered g_all leaves as
            # [K(replica), shard...], so this is shard-local work
            def per_k(gk):
                return upd.update(cfg_sharded, gk, inner_sh, iteration,
                                  lr_overrides, params=p_sh)

            updates_k, new_inner_k = jax.vmap(per_k)(g_all)
            if policy is not None:
                lr_scale_k = stab_k["lr_scale"]
                if policy.skip_nonfinite:
                    sc_k = jnp.where(fin_f > 0, lr_scale_k, 0.0)
                    updates_k = jax.tree_util.tree_map(
                        lambda u: jnp.where(rk(fin_f, u) > 0, u,
                                            jnp.zeros_like(u))
                        * rk(sc_k, u), updates_k)
                    new_inner_k = jax.tree_util.tree_map(
                        lambda n, o: jnp.where(rk(fin_f, n) > 0, n,
                                               o[None].astype(n.dtype)),
                        new_inner_k, inner_sh)
                    new_ns_k = jax.tree_util.tree_map(
                        lambda n, o: jnp.where(rk(fin_f, n) > 0, n, o),
                        new_ns_k, ns_k)
                else:
                    updates_k = jax.tree_util.tree_map(
                        lambda u: u * rk(lr_scale_k, u), updates_k)
            u_mean = jax.tree_util.tree_map(wavg_k, updates_k)
            new_p = dict(p_sh)
            for ln, u in u_mean.items():
                new_p[ln] = upd.apply_updates(p_sh[ln], u)
            new_upd: Dict[str, Any] = jax.tree_util.tree_map(wavg_k,
                                                             new_inner_k)
            ns_out = jax.tree_util.tree_map(wavg_bcast, new_ns_k)
            if policy is not None:
                new_stab_k = jax.vmap(
                    lambda s, f: stability.next_state(policy, s, f))(
                    stab_k, fin_k)
                new_upd[stability.STATE_KEY] = jax.tree_util.tree_map(
                    wavg_bcast, new_stab_k)
            if plan is not None:
                un = zero_mod.update_delta_norms(plan, p_sh, new_p)
                pn = zero_mod.tree_norms(plan, p_sh)
                new_upd[introspection.STATE_KEY] = \
                    zero_mod.pack_introspection(plan, iteration, gn_k, un,
                                                pn, act_k)
            if num_k is not None:
                # stale carry-through (see the docstring): structurally
                # intact so checkpoints and later non-ZeRO fits resume it
                new_upd[numerics.STATE_KEY] = num_k
            losses = losses_k[None]
            if policy is not None:
                return (new_p, new_upd, ns_out, losses, 1.0 - fin_f,
                        jnp.sum(1.0 - fin_f))
            return new_p, new_upd, ns_out, losses

        self._step_fn = instrument(
            jax.jit(fit_window, donate_argnums=(0, 1, 2)),
            "ParallelWrapper.fit_window_zero", argnums=(3, 4, 5, 6, 7, 8, 9))

    # -- fit ---------------------------------------------------------------
    def fit(self, iterator):
        """Train over an iterator of DataSets.  Each averaging window
        consumes ``workers * averaging_frequency`` minibatches (reference
        split sizing ``ParameterAveragingTrainingMaster.java:315-321``).

        Window assembly never runs on the dispatch thread: in-memory
        unmasked data goes through the native C++ slab pipeline
        (``native.Batcher`` producing whole [F*K*B] windows in one gather);
        everything else is stacked by the ``_WindowAssembler`` prefetch
        thread."""
        from deeplearning4j_tpu.datasets.iterator import (
            AsyncDataSetIterator, DataSetIterator, ListDataSetIterator,
        )
        from deeplearning4j_tpu.resilience import (
            FitResilience, get_fault_injector, preemption_requested,
        )

        if self._step_fn is None:
            self._build()

        net = self.net
        res = None
        if self.checkpoint_manager is not None or self.retry_policy is not None:
            # resume BEFORE replica stacking so the restored params are
            # what gets broadcast to the K replicas
            res = FitResilience("parallel_wrapper", self.checkpoint_manager,
                                self.retry_policy, net=net, mesh=self.mesh)
        K, F = self.workers, self.averaging_frequency
        policy = net.conf.stability
        if policy is not None:
            from deeplearning4j_tpu.resilience import stability

            # stability state must exist BEFORE replica stacking so the
            # per-replica guard/scale scalars ride in upd_k
            stability.ensure_state(net)
            if self._stab_rt is None:
                self._stab_rt = stability.StabilityRuntime(
                    "parallel_wrapper", policy,
                    worker_ids=[str(k) for k in range(K)])
        stab_rt = self._stab_rt
        introspect = getattr(net.conf, "introspection", None) is not None
        if introspect:
            from deeplearning4j_tpu.observability import introspection

            # introspection state must exist BEFORE replica stacking so
            # the per-layer stat vectors ride in upd_k as [K, L]
            introspection.ensure_state(net)
        numerics_on = getattr(net.conf, "numerics", None) is not None
        if numerics_on:
            from deeplearning4j_tpu.observability import numerics

            # precision-ledger state rides in upd_k as [K, N] likewise
            numerics.ensure_state(net)
        shard = self._replica_sharding()
        params_k, upd_k, ns_k = self._stage(net, K, shard)
        # sharding ledger over the staged trees, measured against the
        # facade's single-model trees: full replication reads K on the
        # stacked replica view; with update_sharding="zero" the params
        # and updater rows read ~1 (only the tiny stacked reserved
        # subtrees stay per replica — recorded in the notes).  Metadata
        # walk only; recorded once per fit, before the first (donating)
        # dispatch.
        shardstats.record_ledger(
            "parallel_wrapper",
            {"params": params_k, "updater_state": upd_k, "net_state": ns_k},
            logical_trees={"params": net.params,
                           "updater_state": net.updater_state,
                           "net_state": net.net_state},
            data_axis_size=K,
            notes=(self._zero_layout.notes()
                   if self._zero_layout is not None else None))

        if (isinstance(iterator, ListDataSetIterator)
                and iterator._data.features_mask is None
                and iterator._data.labels_mask is None):
            windows = self._native_windows(iterator)
        else:
            if isinstance(iterator, DataSetIterator) and iterator.async_supported():
                iterator = AsyncDataSetIterator(iterator, self.prefetch_size)
            windows = _WindowAssembler(iterator, K, F, self._stack_window,
                                       prefetch=self.prefetch_size)

        get_registry().gauge(
            "dl4j_parallel_replicas",
            "Data-parallel replica count of the active ParallelWrapper",
        ).set(K)
        if self.collect_worker_stats and self._workers is None:
            if self._elastic is not None:
                self._workers = self._elastic.cfg.make_worker_telemetry(
                    "parallel_wrapper")
            else:
                self._workers = WorkerTelemetry("parallel_wrapper")
        if self._elastic is not None and self._workers is not None:
            self._elastic.attach_detector(self._workers.detector)
        it0 = it = net.iteration
        last_losses = None
        win_iter = iter(windows)
        while True:
            t_wait0 = time.perf_counter()
            with self._phases.phase("wait_window"):
                win = next(win_iter, None)
            wait_s = time.perf_counter() - t_wait0
            if win is None:
                break
            xs, ys, fms, lms, n_batches, pad_w = win
            adv = n_batches // K
            if res is not None and res.skip_window(adv):
                # auto-resume: consume the window the restored iteration
                # already covers (it stays put — restore set it past these)
                continue
            if preemption_requested():
                self._fold_back(net, params_k, upd_k, ns_k, it, last_losses)
                if res is not None:
                    res.on_preempt(net)
                if hasattr(windows, "close"):
                    windows.close()
                self.iteration = it - it0
                return net
            weights = self._window_weights(it, pad_w)
            inj = get_fault_injector()
            if inj is not None and inj.has_poison():
                # deterministic chaos: replica k's slot is xs[:, k]
                xs = inj.poison_replica_slots(
                    [str(k) for k in range(K)], it, xs)
            t_disp0 = time.perf_counter()
            with step_guard("parallel_window",
                            component="parallel_wrapper", iteration=it):
                with self._phases.phase("dispatch"):

                    def dispatch(params_k=params_k, upd_k=upd_k, ns_k=ns_k,
                                 weights=weights):
                        rngs = jax.random.split(
                            self.net._keys.next(),
                            xs.shape[0] * K).reshape(xs.shape[0], K)
                        return self._step_fn(
                            params_k, upd_k, ns_k, jnp.asarray(float(it)),
                            jnp.asarray(xs), jnp.asarray(ys), rngs, fms, lms,
                            jnp.asarray(weights))

                    if res is not None:
                        out = res.step(dispatch, it, net=net)
                    else:
                        out = dispatch()
                    if stab_rt is not None:
                        (params_k, upd_k, ns_k, last_losses,
                         poison_k, nf_ct) = out
                        # device-side adds only; read at check boundaries
                        stab_rt.accumulate(nf_ct, poison_k)
                    else:
                        params_k, upd_k, ns_k, last_losses = out
                if self.collect_worker_stats:
                    self._publish_worker_stats(
                        last_losses, time.perf_counter() - t_disp0,
                        wait_s, xs)
            if self._elastic is not None:
                # synchrony-barrier simulation (outside the telemetry
                # window so per-worker attribution stays per-worker):
                # lockstep pays the slowest ACTIVE worker's injected
                # delay; degraded mode's win is the stall it stops paying
                self._elastic.window_barrier(it)
            it += adv
            if stab_rt is not None:
                from deeplearning4j_tpu.resilience import stability

                action = stab_rt.poll_master(
                    step=it, losses=last_losses, elastic=self._elastic,
                    # stacked [K] scale state: feeds the loss-scale /
                    # lr-scale gauges at check boundaries (nonfinite
                    # totals still come from the window accumulator)
                    stab_state=upd_k.get(stability.STATE_KEY),
                    can_rewind=res is not None and res.cm is not None)
                if action == "backoff":
                    upd_k = stability.apply_lr_backoff_tree(upd_k, policy)
                elif action == "rewind":
                    self._fold_back(net, params_k, upd_k, ns_k, it,
                                    last_losses)
                    if stab_rt.rewind(net, res.cm) is not None:
                        # restage the rewound facade state onto the mesh
                        it = net.iteration
                        params_k, upd_k, ns_k = self._stage(net, K, shard)
            if introspect:
                from deeplearning4j_tpu.observability import introspection

                # stacked [K, L] per-replica view for harvesters — a
                # device reference only, no transfer until a listener's
                # reporting interval actually reads it
                net._introspect_live = upd_k.get(introspection.STATE_KEY)
            if numerics_on:
                from deeplearning4j_tpu.observability import numerics

                # stacked [K, N] per-replica precision-ledger view
                net._numerics_live = upd_k.get(numerics.STATE_KEY)
            if net.listeners:
                # fire the facade's listeners once per averaging window
                # (reference ParallelWrapper notifies per iteration) with
                # the averaged state folded back — device-side slices,
                # no host sync unless a listener reads a value
                from deeplearning4j_tpu.models.common import notify_listeners

                self._fold_back(net, params_k, upd_k, ns_k, it, last_losses)
                # sample count excludes pad-filled tail slots (each zero
                # in pad_w is one duplicated/zero-filled minibatch slot)
                # so listener throughput reflects REAL examples; pad_w is
                # a host-built numpy [K] vector (_pad_weights), no sync
                real_slots = n_batches - (
                    0 if pad_w is None else int((pad_w == 0.0).sum()))
                notify_listeners(
                    net, real_slots
                    * (int(xs.shape[2]) if xs.ndim >= 3 else 1))
            self._phases.steps += 1
            if res is not None and res.cm is not None:
                trigger = res.cm.due(it)
                if trigger is not None:
                    # fold the averaged replica-0 state into the facade
                    # only when a save is actually due
                    self._fold_back(net, params_k, upd_k, ns_k, it,
                                    last_losses)
                    res.cm.save(net, trigger=trigger)

        self._fold_back(net, params_k, upd_k, ns_k, it, last_losses)
        if stab_rt is not None:
            stab_rt.flush(net)   # tail past the last check boundary
        self.iteration = it - it0
        return net

    def _window_weights(self, it: int, pad_w):
        """Combine the elastic eviction mask with the tail-padding weights
        into the [K] weight vector the jitted window consumes.  The
        all-ones fast path covers every healthy full window.  When every
        replica holding real data is also evicted (pathological overlap of
        a ragged tail with a degraded mesh), the eviction mask alone wins
        — training on a duplicate minibatch beats dividing by zero or
        averaging in a dead replica."""
        mask = None
        if self._elastic is not None:
            mask = self._elastic.begin_window(it)
            if mask.min() >= 1.0:
                mask = None
        if mask is None and pad_w is None:
            if self._ones_w is None or len(self._ones_w) != self.workers:
                self._ones_w = np.ones(self.workers, np.float32)
            return self._ones_w
        if mask is None:
            return pad_w
        if pad_w is None:
            return mask
        combined = mask * pad_w
        return combined if combined.sum() > 0 else mask

    def _stage(self, net, K, shard):
        """Stage the facade's trees onto the mesh: stacked ``[K, ...]``
        replicas (replicated mode) or the ZeRO layout — params + inner
        updater slots sharded 1/K per device, the reserved subtrees and
        net state stacked per replica as in replicated mode."""
        if self.update_sharding == zero_mod.ZERO:
            layout = self._zero_layout
            params_z = layout.place(net.params)
            upd_z = (layout.place_updater(
                net.updater_state,
                reserved_place=lambda t: jax.device_put(
                    _stack_tree(t, K), shard))
                if net.updater_state else {})
            ns_z = _stack_tree(net.net_state, K)
            if net.net_state:
                ns_z = jax.device_put(ns_z, shard)
            return params_z, upd_z, ns_z
        params_k = jax.device_put(_stack_tree(net.params, K), shard)
        upd_k = _stack_tree(net.updater_state, K)
        if net.updater_state:
            upd_k = jax.device_put(upd_k, shard)
        ns_k = _stack_tree(net.net_state, K)
        if net.net_state:
            ns_k = jax.device_put(ns_k, shard)
        return params_k, upd_k, ns_k

    def _fold_back(self, net, params_k, upd_k, ns_k, it, last_losses):
        """Fold the averaged replica-0 state back into the facade (loop
        end, window-boundary checkpoint saves, preemption stop).  Under
        ZeRO the params / inner updater leaves are already the single
        logical copy (sharded jax arrays — the facade, the checkpoint
        writer and ``net.output`` consume them directly); only the
        stacked reserved subtrees and net state take the replica-0
        slice."""
        if self.update_sharding == zero_mod.ZERO:
            net.params = params_k
            net.updater_state = {
                slot: (jax.tree_util.tree_map(lambda a: a[0], tree)
                       if slot in shardstats.RESERVED_REPLICATED_SUBTREES
                       else tree)
                for slot, tree in upd_k.items()}
            net.net_state = jax.tree_util.tree_map(lambda a: a[0], ns_k)
        else:
            net.params = jax.tree_util.tree_map(lambda a: a[0], params_k)
            net.updater_state = jax.tree_util.tree_map(lambda a: a[0],
                                                       upd_k)
            net.net_state = jax.tree_util.tree_map(lambda a: a[0], ns_k)
        if last_losses is not None:
            net.score_value = last_losses[-1].mean()  # device scalar; lazy
        net.iteration = it

    def phase_stats(self):
        """Per-phase wall-time aggregates of this wrapper's fit loop
        (same schema as ``TrainingMaster.training_stats()['phases']``)."""
        return self._phases.as_dict()

    # -- per-worker diagnosis ---------------------------------------------
    def _worker_step_times(self, losses, dispatch_s: float) -> Dict[str, float]:
        """Per-replica completion time of the last window: blocks on each
        replica's loss shard in device order and adds its arrival offset
        to the dispatch time.

        Measurement honesty: the window program ends in the parameter-
        averaging all-reduce, and a collective gates every device on the
        slowest one — so shard readiness reflects the CLUSTER critical
        path (the slow replica sets everyone's time), not per-replica
        blame, and the sequential poll means a slow first-polled shard
        masks later ones.  What this yields in-process is an accurate
        cluster step-time distribution (the thing SLO rules and p99s
        read).  Per-replica ATTRIBUTION comes from feeding
        ``WorkerTelemetry.observe`` with externally measured times — a
        multi-process driver timing its own host, a chaos harness, or
        the tests — through exactly this seam (override this method).
        When the loss is not addressably sharded per replica, the whole
        window is synced and its WALL time (dispatch + execution — not
        just the async enqueue time, which would report microsecond
        "steps" and wildly inflated throughput) is attributed to every
        worker."""
        K = self.workers

        def blocked_total() -> Dict[str, float]:
            t0 = time.perf_counter()
            try:
                jax.block_until_ready(losses)
            except Exception:
                pass
            total = dispatch_s + (time.perf_counter() - t0)
            return {str(k): total for k in range(K)}

        if losses is None:
            return {str(k): dispatch_s for k in range(K)}
        try:
            shards = list(losses.addressable_shards)
        except Exception:
            return blocked_total()
        if len(shards) < 2:
            return blocked_total()
        times = {str(k): dispatch_s for k in range(K)}
        t0 = time.perf_counter()
        for sh in shards:
            try:
                jax.block_until_ready(sh.data)
            except Exception:
                continue
            arrive = time.perf_counter() - t0
            idx = sh.index  # slices into the global [F, K] loss array
            if (isinstance(idx, tuple) and len(idx) >= 2
                    and isinstance(idx[1], slice)):
                for k in range(*idx[1].indices(K)):
                    times[str(k)] = dispatch_s + arrive
        return times

    def _publish_worker_stats(self, losses, dispatch_s: float,
                              wait_s: float, xs) -> None:
        from deeplearning4j_tpu.resilience import get_fault_injector

        F = max(1, int(xs.shape[0]))
        B = int(xs.shape[2]) if xs.ndim >= 3 else None
        times = self._worker_step_times(losses, dispatch_s)
        inj = get_fault_injector()
        if inj is not None:
            # deterministic chaos: an injected per-worker delay makes the
            # straggler detector's input reproducible in tests
            times = {w: t + inj.worker_delay(w) for w, t in times.items()}
        for worker, t in times.items():
            self._workers.observe(
                worker, t / F, batch=B,
                phases={"wait_window": wait_s / F, "dispatch": t / F})

    def cluster_stats(self) -> Dict[str, Any]:
        """Merged per-replica view (mean/p50/p99/max step time, slowest
        worker, total throughput) — empty before the first window or when
        ``collect_worker_stats=False``."""
        return self._workers.cluster_view() if self._workers else {}

    @property
    def straggler_detector(self):
        return self._workers.detector if self._workers else None

    def _stack_window(self, window, n_real=None):
        """Host half of a window step: pad + stack to [F, K, B, ...].
        Runs on the assembler thread, not the dispatch thread.

        ``n_real`` is the count of REAL minibatches in ``window`` — the
        assembler duplicates the tail minibatch to fill the last row of K
        replica slots, and those pad-filled slots must be weighted out of
        the window's parameter average or the duplicate is double-counted
        (the tail-window bias fix; ``_pad_weights``)."""
        K = self.workers
        F = len(window) // K
        # equalize batch sizes across the window (short/ragged final batches)
        max_b = max(len(w) for w in window)
        window = [w.pad_batch(max_b) if len(w) < max_b else w for w in window]
        xs = np.stack([np.stack([w.features for w in window[f * K : (f + 1) * K]]) for f in range(F)])
        ys = np.stack([np.stack([w.labels for w in window[f * K : (f + 1) * K]]) for f in range(F)])
        fms = self._stack_masks([w.features_mask for w in window], K, F)
        lms = self._stack_masks([w.labels_mask for w in window], K, F)
        n_real = len(window) if n_real is None else n_real
        return xs, ys, fms, lms, len(window), \
            self._pad_weights(n_real, len(window))

    def _pad_weights(self, n_real: int, n_slots: int):
        """[K] replica weights for a window whose minibatch slots past
        ``n_real`` are padding (duplicated tail batch in the generic path,
        zero-filled batches in the native path), or None when full.  Slot
        ``i`` belongs to replica ``i % K`` (rows are contiguous K-blocks),
        and the padding always lands in the last row, so a zero weight
        names exactly the replicas whose final scan step saw no real
        data."""
        if n_real >= n_slots:
            return None
        w = np.ones(self.workers, np.float32)
        for i in range(n_real, n_slots):
            w[i % self.workers] = 0.0
        return w

    def _native_windows(self, iterator):
        """Whole windows as single native gathers: the C++ producer thread
        assembles a contiguous [F*K*B] slab per window (row-major order
        matches the reference's sequential minibatch grouping).  The ragged
        tail honors the iterator's drop_last and is emitted as a TRUNCATED
        window — only as many (K-padded) batch rows as the data fills, with
        a labels mask on the zero-padded remainder — so iteration counts and
        score semantics track the generic path."""
        from deeplearning4j_tpu import native

        K, F = self.workers, self.averaging_frequency
        B = iterator.batch()
        data = iterator._data
        n = len(data)
        if getattr(iterator, "_drop_last", False):
            n = (n // B) * B  # generic path drops the ragged final batch
            if n == 0:
                return
            data = data.subset(slice(0, n))
        slab = B * K * F
        batcher = native.Batcher(data.features, data.labels, slab,
                                 shuffle=False, seed=1, drop_last=False,
                                 queue_cap=max(1, self.prefetch_size))
        try:
            while True:
                out = batcher.next()
                if out is None:
                    return
                feat, lab, n_valid = out
                if n_valid == slab:
                    xs = feat.reshape((F, K, B) + feat.shape[1:])
                    ys = lab.reshape((F, K, B) + lab.shape[1:])
                    yield xs, ys, None, None, F * K, None
                    continue
                # tail: keep only the batches the data actually fills, and
                # emit the FULL frames as their own window first (a
                # whole-tail per-replica weight would also discard those
                # replicas' real earlier minibatches from the average)
                nb = -(-n_valid // B)          # ceil: batches with any data
                f_full = nb // K               # complete K-replica frames
                mshape = ((nb * B,) if lab.ndim == 2
                          else (nb * B, lab.shape[1]))
                m = np.zeros(mshape, np.float32)
                m[:n_valid] = 1.0

                def part(lo_b, n_b, n_real_b):
                    """Window over batch slots [lo_b, lo_b + n_b)."""
                    rows = slice(lo_b * B, (lo_b + n_b) * B)
                    xs = feat[rows].reshape(
                        (n_b // K, K, B) + feat.shape[1:])
                    ys = lab[rows].reshape((n_b // K, K, B) + lab.shape[1:])
                    mp = np.zeros((n_b * B,) + m.shape[1:], np.float32)
                    avail = min(len(m) - lo_b * B, n_b * B)
                    if avail > 0:
                        mp[:avail] = m[lo_b * B:lo_b * B + avail]
                    lms = (None if mp.all() else jnp.asarray(
                        mp.reshape((n_b // K, K, B) + mp.shape[1:])))
                    # replicas whose batch slot is entirely zero padding
                    # are weighted out of the average: the labels mask
                    # already zeroes their LOSS, but a zero-grad step
                    # still mutates stateful updaters (Adam moments
                    # decay), so averaging their params back in would
                    # bias toward the pad
                    return (xs, ys, None, lms, n_b,
                            self._pad_weights(n_real_b - lo_b, n_b))

                if f_full:
                    yield part(0, f_full * K, nb)
                if nb % K:
                    yield part(f_full * K, K, nb)
        finally:
            batcher.close()

    @staticmethod
    def _stack_masks(masks, K, F):
        if all(m is None for m in masks):
            return None
        shaped = [np.asarray(m) for m in masks if m is not None]
        template = np.ones_like(shaped[0])
        masks = [np.asarray(m) if m is not None else template for m in masks]
        return jnp.asarray(
            np.stack([np.stack(masks[f * K : (f + 1) * K]) for f in range(F)])
        )
