"""Sharded checkpointing — per-host shard files for mesh-sharded training.

Reference scale-up analog: ``util/ModelSerializer.java:32-95`` writes one
zip from one JVM; a TPU pod slice cannot funnel params through one host, so
here every process writes ONLY its addressable shards to its own
``shards-<process>.npz`` plus a JSON manifest recording, per leaf, the
global shape/dtype, the ``PartitionSpec``, and the global slices each saved
shard covers.  Restore reassembles each leaf from whatever shard files are
visible on (shared) storage and ``device_put``s it with the original
NamedSharding reconstructed over the caller's mesh — so a checkpoint taken
on one mesh restores onto any mesh with the same axis names.

Topology portability ("Memory-efficient array redistribution through
portable collective communication", arXiv 2112.01075): with ``mesh=``
given, restore never gathers a sharded leaf to one host buffer.  When the
target has the same device count as the saver (a 2x4 checkpoint resuming
on 1x8), each saved shard is loaded straight onto a device in the SAVED
layout and one device-side resharding program (XLA collective permutes /
all-gathers over ICI) redistributes it to the target layout.  When the
device count changed (K=4 -> K=2, or a single-device debug restore), each
TARGET shard is assembled host-side from only the saved file shards that
intersect it — host memory is bounded by one device's shard, not the leaf.
Saved axes missing from the target mesh (or no longer dividing the dim)
degrade to replication for that dimension.

Resumability: ``iteration`` and the facade's KeyStream root key are saved,
so a restored run replays the exact key sequence the uninterrupted run
would have used (resume-equivalence is the test oracle,
``tests/test_checkpoint_sharded.py``).

Single-file portability (``ModelSerializer`` parity) stays in
``models/serialization.py``; this module is the multi-chip/multi-host path.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MANIFEST = "manifest-{proc}.json"
SHARDS = "shards-{proc}.npz"
META = "checkpoint.json"


# --------------------------------------------------------------- tree <-> flat
def _flatten(tree, prefix=""):
    """Flatten nested dicts to {path: leaf}; path segments joined by '/'."""
    out = {}
    for k in sorted(tree):
        v = tree[k]
        p = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, p + "/"))
        else:
            out[p] = v
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, v in flat.items():
        cur = out
        keys = path.split("/")
        for k in keys[:-1]:
            cur = cur.setdefault(k, {})
        cur[keys[-1]] = v
    return out


def _spec_to_json(spec) -> list:
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _leaf_spec(leaf) -> list:
    sh = getattr(leaf, "sharding", None)
    if isinstance(sh, NamedSharding):
        return _spec_to_json(sh.spec)
    return []  # replicated / single-device / host array


# ------------------------------------------------------------------------ save
def snapshot_trees(net, *, trees: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Host-side snapshot of this process's shards of the facade's params /
    updater state / net state (or explicit ``trees``) plus iteration + RNG
    root key.  This is the device->host half of a save: it walks
    ``addressable_shards`` and copies every shard to numpy, so it must run
    on the training thread at a step boundary — but the returned structure
    is plain host data, safe to hand to a background writer thread
    (``resilience.CheckpointManager`` does exactly that)."""
    proc = jax.process_index()
    trees = trees if trees is not None else {
        "params": net.params,
        "updater_state": net.updater_state,
        "net_state": net.net_state,
    }
    manifest: Dict[str, Any] = {"leaves": {}}
    arrays: Dict[str, np.ndarray] = {}
    for tname, tree in trees.items():
        for path, leaf in _flatten(tree, f"{tname}/").items():
            leaf = jnp.asarray(leaf)
            lsh = getattr(leaf, "sharding", None)
            if isinstance(lsh, NamedSharding) and "mesh" not in manifest:
                # saver topology on record: the resharded-restore fast
                # path lays the SAVED layout over the target's devices to
                # redistribute device-side (module docstring)
                manifest["mesh"] = {
                    "axis_names": [str(a) for a in lsh.mesh.axis_names],
                    "shape": [int(s) for s in
                              np.asarray(lsh.mesh.devices).shape],
                }
            entry = {
                "shape": list(leaf.shape),
                "dtype": str(leaf.dtype),
                "spec": _leaf_spec(leaf),
                "shards": [],
            }
            if hasattr(leaf, "addressable_shards") and leaf.addressable_shards:
                seen = set()
                for shard in leaf.addressable_shards:
                    idx = tuple(
                        (0 if s.start is None else int(s.start),
                         dim if s.stop is None else int(s.stop))
                        for s, dim in zip(shard.index, leaf.shape))
                    if idx in seen:  # replicated copies: store once
                        continue
                    full = all(a == 0 and b == d
                               for (a, b), d in zip(idx, leaf.shape))
                    if full and proc != 0:
                        # cross-host-replicated leaf: process 0's copy is
                        # authoritative; storing N copies would grow a
                        # pure-DP checkpoint N-fold
                        continue
                    seen.add(idx)
                    # process-qualified key: every host writes its own npz,
                    # and restore merges ALL manifests, so keys must be
                    # globally unique across processes
                    key = f"p{proc}/{path}@{len(entry['shards'])}"
                    arrays[key] = np.asarray(shard.data)
                    entry["shards"].append({"key": key, "index": [list(i) for i in idx]})
            else:
                key = f"p{proc}/{path}@0"
                arrays[key] = np.asarray(leaf)
                entry["shards"].append({
                    "key": key,
                    "index": [[0, d] for d in leaf.shape]})
            manifest["leaves"][path] = entry
    meta = None
    if proc == 0:
        meta = {
            "format_version": 1,
            "iteration": int(getattr(net, "iteration", 0)),
            "processes": jax.process_count(),
        }
        keys = getattr(net, "_keys", None)
        if keys is not None:
            meta["rng_key"] = np.asarray(
                jax.random.key_data(keys._key)).tolist()
    return {
        "proc": proc,
        "manifest": manifest,
        "arrays": arrays,
        "meta": meta,
        "iteration": int(getattr(net, "iteration", 0)),
    }


def write_snapshot(directory: str, snapshot: Dict[str, Any], *,
                   fsync: bool = False, on_file=None) -> int:
    """File half of a save: write a ``snapshot_trees`` result into
    ``directory`` (shards first, then manifest, then meta — the order a
    torn write is cheapest to detect in).  Pure host IO, safe off the
    training thread.  ``on_file(path)`` fires after each file lands
    (the ``FaultInjector`` crash-mid-save hook); with ``fsync`` every file
    is flushed to disk before the call returns — the atomic-commit rename
    in ``resilience.CheckpointManager`` relies on that ordering.  Returns
    total bytes written by this process."""
    os.makedirs(directory, exist_ok=True)
    proc = snapshot["proc"]
    total = 0

    def _land(path, write_fn, mode):
        nonlocal total
        with open(path, mode) as f:
            write_fn(f)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        total += os.path.getsize(path)
        if on_file is not None:
            on_file(path)

    _land(os.path.join(directory, SHARDS.format(proc=proc)),
          lambda f: np.savez(f, **snapshot["arrays"]), "wb")
    _land(os.path.join(directory, MANIFEST.format(proc=proc)),
          lambda f: json.dump(snapshot["manifest"], f), "w")
    if snapshot["meta"] is not None:
        _land(os.path.join(directory, META),
              lambda f: json.dump(snapshot["meta"], f), "w")
    return total


def save_checkpoint(directory: str, net, *, trees: Optional[Dict[str, Any]] = None) -> None:
    """Write this process's shards of the facade's params / updater state /
    net state (or explicit ``trees``) plus iteration + RNG root key.

    NOTE: this low-level call writes straight into the live ``directory``
    — a crash mid-save leaves a torn checkpoint there.  Production runs
    should save through ``resilience.CheckpointManager``, which stages the
    same files in a ``step-N.tmp/`` directory and commits them atomically
    (tmp -> fsync -> rename + COMMIT manifest)."""
    snapshot = snapshot_trees(net, trees=trees)
    write_snapshot(directory, snapshot)
    from deeplearning4j_tpu.observability import get_flight_recorder

    get_flight_recorder().record(
        "checkpoint", directory=str(directory), process=snapshot["proc"],
        iteration=snapshot["iteration"])


# --------------------------------------------------------------------- restore
def _saved_shards(entry, shard_files):
    """Every saved piece of a leaf present in the loaded npz files, as
    ``(ranges, lazy-loaded array)`` with ``ranges`` the global [start,
    stop) per dim.  npz members decompress on access, so iterating here
    reads only the pieces the caller actually indexes into."""
    out = []
    for s in entry["shards"]:
        for npz in shard_files:
            if s["key"] in npz:
                out.append((tuple((int(a), int(b)) for a, b in s["index"]),
                            npz, s["key"]))
                break
    return out


def _assemble_slice(entry, shard_files, ranges, member_cache=None
                    ) -> np.ndarray:
    """Assemble ONE hyperrectangle ``ranges`` of a leaf from the saved
    file shards that intersect it — the host-memory footprint is the
    slice, never the global leaf (the no-gather half of the resharded
    restore).  ``member_cache`` (a dict reused across calls for one leaf)
    keeps the most recently loaded npz member: NpzFile decompresses the
    whole member on every access, so without it a target mesh finer than
    the saver re-reads each saved shard once per intersecting target
    shard.  Target ranges arrive in device (row-major) order, so a
    one-entry cache removes that amplification while holding at most one
    extra saved shard on the host."""
    def load(npz, key):
        if member_cache is None:
            return npz[key]
        ck = (id(npz), key)
        if member_cache.get("key") != ck:
            member_cache["key"] = ck
            member_cache["val"] = npz[key]
        return member_cache["val"]

    dtype = np.dtype(entry["dtype"])
    if not ranges and not entry["shape"]:        # scalar leaf
        for _rg, npz, key in _saved_shards(entry, shard_files):
            return load(npz, key).astype(dtype)
        raise ValueError(
            f"checkpoint incomplete: leaf {entry} missing shard data "
            f"(multi-host checkpoint restored without shared storage?)")
    shape = tuple(b - a for a, b in ranges)
    out = np.zeros(shape, dtype)
    filled = np.zeros(shape, bool)
    for rg, npz, key in _saved_shards(entry, shard_files):
        inter = [(max(a, c), min(b, d))
                 for (a, b), (c, d) in zip(ranges, rg)]
        if any(lo >= hi for lo, hi in inter):
            continue
        dst = tuple(slice(lo - a, hi - a)
                    for (lo, hi), (a, _b) in zip(inter, ranges))
        src = tuple(slice(lo - c, hi - c)
                    for (lo, hi), (c, _d) in zip(inter, rg))
        out[dst] = load(npz, key)[src]
        filled[dst] = True
    if not bool(filled.all()):
        raise ValueError(
            f"checkpoint incomplete: leaf {entry} missing shard data "
            f"(multi-host checkpoint restored without shared storage?)")
    return out


def _fit_spec(entries, mesh: Mesh, shape) -> PartitionSpec:
    """Adapt a saved PartitionSpec (json form) to ``mesh``: a dimension
    keeps its saved axes only when every axis exists on the target mesh
    and their product still divides the dimension; otherwise it degrades
    to replicated (None) for that dim."""
    parts = []
    for d in range(len(shape)):
        e = entries[d] if d < len(entries) else None
        names = (tuple(e) if isinstance(e, (list, tuple))
                 else (e,) if e is not None else ())
        if names and all(n in mesh.shape for n in names):
            sz = 1
            for n in names:
                sz *= mesh.shape[n]
            if sz and shape[d] % sz == 0:
                parts.append(tuple(e) if isinstance(e, list) else e)
                continue
        parts.append(None)
    while parts and parts[-1] is None:   # P('data', None) -> P('data')
        parts.pop()
    return PartitionSpec(*parts)


def _index_ranges(idx, shape):
    idx = tuple(idx) + (slice(None),) * (len(shape) - len(idx))
    return tuple((0 if s.start is None else int(s.start),
                  dim if s.stop is None else int(s.stop))
                 for s, dim in zip(idx, shape))


def _build_in_sharding(entry, shard_files, sharding: NamedSharding, shape):
    """Materialize a leaf directly in ``sharding`` by assembling each
    device's shard and stitching with
    ``make_array_from_single_device_arrays`` — no global host buffer.
    The dedup cache keys DEVICE buffers (replicated ranges copy
    device-to-device), so the host holds at most ONE slice at a time
    however many distinct shards the leaf has."""
    idx_map = sharding.addressable_devices_indices_map(shape)
    placed: Dict[Any, Any] = {}
    member_cache: Dict[str, Any] = {}
    arrays = []
    for dev, idx in idx_map.items():
        ranges = _index_ranges(idx, shape)
        have = placed.get(ranges)
        if have is None:
            host = _assemble_slice(entry, shard_files, ranges,
                                   member_cache)
            have = placed[ranges] = jax.device_put(host, dev)
            del host
            arrays.append(have)
        else:
            arrays.append(jax.device_put(have, dev))
    return jax.make_array_from_single_device_arrays(shape, sharding, arrays)


def _source_sharding(man_mesh, mesh: Mesh, entry, shape):
    """The SAVED layout laid over the TARGET mesh's devices (same device
    count required), or None.  This is the loading layout of the
    device-side resharding fast path: saved shards go to devices as-is,
    then one compiled identity with target out_shardings redistributes
    via collective permutes."""
    if not man_mesh:
        return None
    devs = np.asarray(mesh.devices).reshape(-1)
    src_shape = tuple(int(s) for s in man_mesh.get("shape", ()))
    if not src_shape or devs.size != int(np.prod(src_shape)):
        return None
    try:
        src_mesh = Mesh(devs.reshape(src_shape),
                        tuple(man_mesh["axis_names"]))
    except Exception:
        return None
    spec = _fit_spec(entry["spec"], src_mesh, shape)
    fitted = _spec_to_json(spec)
    fitted += [None] * (len(shape) - len(fitted))
    saved = list(entry["spec"])
    saved += [None] * (len(shape) - len(saved))
    if fitted != saved:
        return None      # must reproduce the saved partitioning exactly
    return NamedSharding(src_mesh, spec)


def _reshard_on_device(arr, target: NamedSharding):
    """Device-side redistribution src-layout -> target-layout.  XLA lowers
    the sharding change to collective permutes / all-gathers over the
    interconnect; the host never sees the global array."""
    try:
        return jax.device_put(arr, target)
    except Exception:
        return jax.jit(lambda a: a, out_shardings=target)(arr)


def _place_leaf(entry, shard_files, mesh: Mesh, man_mesh=None):
    """Restore one leaf onto ``mesh`` without a global host gather
    (module docstring: fast path when the saver's device count matches,
    per-target-shard assembly otherwise)."""
    shape = tuple(entry["shape"])
    target = NamedSharding(mesh, _fit_spec(entry["spec"], mesh, shape))
    if not shape:
        return jax.device_put(_assemble_slice(entry, shard_files, ()),
                              target)
    src = _source_sharding(man_mesh, mesh, entry, shape)
    if src is not None:
        try:
            same_layout = src.is_equivalent_to(target, len(shape))
        except Exception:
            same_layout = src.spec == target.spec
        if not same_layout:
            loaded = _build_in_sharding(entry, shard_files, src, shape)
            return _reshard_on_device(loaded, target)
    return _build_in_sharding(entry, shard_files, target, shape)


def _assemble(entry, shard_files) -> np.ndarray:
    """Full-leaf host assembly — the explicit gather-to-host reference
    path (``mesh=None``).  The resharded restore never calls this (the
    matrix tests pin that); kept as a separate seam rather than inlined so
    the two paths stay monkeypatch-distinguishable."""
    return _assemble_slice(entry, shard_files,
                           tuple((0, d) for d in entry["shape"]))


def restore_checkpoint(directory: str, net=None, *, mesh: Optional[Mesh] = None
                       ) -> Tuple[Dict[str, Any], Dict[str, Any], Dict[str, Any], int]:
    """Reassemble (params, updater_state, net_state, iteration).  With
    ``net`` given, restores in place (incl. iteration + RNG stream).  With
    ``mesh`` given, leaves are placed with their saved PartitionSpec
    adapted to that mesh via the resharded-restore path — ANY saved
    topology restores onto ANY target mesh with no global host gather of
    a sharded leaf (module docstring).  Without a mesh they come back as
    host-backed arrays (the explicit gather-to-host reference path)."""
    manifests = []
    shard_files = []
    for fn in sorted(os.listdir(directory)):
        if fn.startswith("manifest-"):
            with open(os.path.join(directory, fn)) as f:
                manifests.append(json.load(f))
        elif fn.startswith("shards-"):
            shard_files.append(np.load(os.path.join(directory, fn)))
    if not manifests:
        raise FileNotFoundError(f"no checkpoint manifests in {directory}")
    # merge per-process manifests: each host recorded only its own shards of
    # a cross-host-sharded leaf, so a leaf's shard list is the UNION over
    # all manifests (shape/dtype/spec agree by construction)
    merged: Dict[str, Any] = {}
    for man in manifests:
        for path, entry in man["leaves"].items():
            if path not in merged:
                merged[path] = {k: entry[k] for k in ("shape", "dtype", "spec")}
                merged[path]["shards"] = list(entry["shards"])
            else:
                have = {s["key"] for s in merged[path]["shards"]}
                merged[path]["shards"] += [s for s in entry["shards"]
                                           if s["key"] not in have]
    man_mesh = None
    for man in manifests:
        if man.get("mesh"):
            man_mesh = man["mesh"]
            break
    leaves: Dict[str, Any] = {}
    for path, entry in merged.items():
        if mesh is not None:
            leaves[path] = _place_leaf(entry, shard_files, mesh, man_mesh)
        else:
            leaves[path] = jnp.asarray(_assemble(entry, shard_files))
    for npz in shard_files:
        npz.close()
    full = _unflatten(leaves)
    params = full.get("params", {})
    upd = full.get("updater_state", {})
    ns = full.get("net_state", {})
    with open(os.path.join(directory, META)) as f:
        meta = json.load(f)
    iteration = int(meta.get("iteration", 0))
    if net is not None:
        net.params = params
        net.updater_state = upd
        net.net_state = ns
        net.iteration = iteration
        if "rng_key" in meta and getattr(net, "_keys", None) is not None:
            net._keys._key = jax.random.wrap_key_data(
                jnp.asarray(np.asarray(meta["rng_key"], np.uint32)))
    return params, upd, ns, iteration
