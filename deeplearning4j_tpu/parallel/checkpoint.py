"""Sharded checkpointing — per-host shard files for mesh-sharded training.

Reference scale-up analog: ``util/ModelSerializer.java:32-95`` writes one
zip from one JVM; a TPU pod slice cannot funnel params through one host, so
here every process writes ONLY its addressable shards to its own
``shards-<process>.npz`` plus a JSON manifest recording, per leaf, the
global shape/dtype, the ``PartitionSpec``, and the global slices each saved
shard covers.  Restore reassembles each leaf from whatever shard files are
visible on (shared) storage and ``device_put``s it with the original
NamedSharding reconstructed over the caller's mesh — so a checkpoint taken
on one mesh restores onto any mesh with the same axis names.

Resumability: ``iteration`` and the facade's KeyStream root key are saved,
so a restored run replays the exact key sequence the uninterrupted run
would have used (resume-equivalence is the test oracle,
``tests/test_checkpoint_sharded.py``).

Single-file portability (``ModelSerializer`` parity) stays in
``models/serialization.py``; this module is the multi-chip/multi-host path.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MANIFEST = "manifest-{proc}.json"
SHARDS = "shards-{proc}.npz"
META = "checkpoint.json"


# --------------------------------------------------------------- tree <-> flat
def _flatten(tree, prefix=""):
    """Flatten nested dicts to {path: leaf}; path segments joined by '/'."""
    out = {}
    for k in sorted(tree):
        v = tree[k]
        p = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, p + "/"))
        else:
            out[p] = v
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, v in flat.items():
        cur = out
        keys = path.split("/")
        for k in keys[:-1]:
            cur = cur.setdefault(k, {})
        cur[keys[-1]] = v
    return out


def _spec_to_json(spec) -> list:
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _spec_from_json(entries) -> PartitionSpec:
    parts = []
    for e in entries:
        if isinstance(e, list):
            parts.append(tuple(e))
        else:
            parts.append(e)
    return PartitionSpec(*parts)


def _leaf_spec(leaf) -> list:
    sh = getattr(leaf, "sharding", None)
    if isinstance(sh, NamedSharding):
        return _spec_to_json(sh.spec)
    return []  # replicated / single-device / host array


# ------------------------------------------------------------------------ save
def snapshot_trees(net, *, trees: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Host-side snapshot of this process's shards of the facade's params /
    updater state / net state (or explicit ``trees``) plus iteration + RNG
    root key.  This is the device->host half of a save: it walks
    ``addressable_shards`` and copies every shard to numpy, so it must run
    on the training thread at a step boundary — but the returned structure
    is plain host data, safe to hand to a background writer thread
    (``resilience.CheckpointManager`` does exactly that)."""
    proc = jax.process_index()
    trees = trees if trees is not None else {
        "params": net.params,
        "updater_state": net.updater_state,
        "net_state": net.net_state,
    }
    manifest: Dict[str, Any] = {"leaves": {}}
    arrays: Dict[str, np.ndarray] = {}
    for tname, tree in trees.items():
        for path, leaf in _flatten(tree, f"{tname}/").items():
            leaf = jnp.asarray(leaf)
            entry = {
                "shape": list(leaf.shape),
                "dtype": str(leaf.dtype),
                "spec": _leaf_spec(leaf),
                "shards": [],
            }
            if hasattr(leaf, "addressable_shards") and leaf.addressable_shards:
                seen = set()
                for shard in leaf.addressable_shards:
                    idx = tuple(
                        (0 if s.start is None else int(s.start),
                         dim if s.stop is None else int(s.stop))
                        for s, dim in zip(shard.index, leaf.shape))
                    if idx in seen:  # replicated copies: store once
                        continue
                    full = all(a == 0 and b == d
                               for (a, b), d in zip(idx, leaf.shape))
                    if full and proc != 0:
                        # cross-host-replicated leaf: process 0's copy is
                        # authoritative; storing N copies would grow a
                        # pure-DP checkpoint N-fold
                        continue
                    seen.add(idx)
                    # process-qualified key: every host writes its own npz,
                    # and restore merges ALL manifests, so keys must be
                    # globally unique across processes
                    key = f"p{proc}/{path}@{len(entry['shards'])}"
                    arrays[key] = np.asarray(shard.data)
                    entry["shards"].append({"key": key, "index": [list(i) for i in idx]})
            else:
                key = f"p{proc}/{path}@0"
                arrays[key] = np.asarray(leaf)
                entry["shards"].append({
                    "key": key,
                    "index": [[0, d] for d in leaf.shape]})
            manifest["leaves"][path] = entry
    meta = None
    if proc == 0:
        meta = {
            "format_version": 1,
            "iteration": int(getattr(net, "iteration", 0)),
            "processes": jax.process_count(),
        }
        keys = getattr(net, "_keys", None)
        if keys is not None:
            meta["rng_key"] = np.asarray(
                jax.random.key_data(keys._key)).tolist()
    return {
        "proc": proc,
        "manifest": manifest,
        "arrays": arrays,
        "meta": meta,
        "iteration": int(getattr(net, "iteration", 0)),
    }


def write_snapshot(directory: str, snapshot: Dict[str, Any], *,
                   fsync: bool = False, on_file=None) -> int:
    """File half of a save: write a ``snapshot_trees`` result into
    ``directory`` (shards first, then manifest, then meta — the order a
    torn write is cheapest to detect in).  Pure host IO, safe off the
    training thread.  ``on_file(path)`` fires after each file lands
    (the ``FaultInjector`` crash-mid-save hook); with ``fsync`` every file
    is flushed to disk before the call returns — the atomic-commit rename
    in ``resilience.CheckpointManager`` relies on that ordering.  Returns
    total bytes written by this process."""
    os.makedirs(directory, exist_ok=True)
    proc = snapshot["proc"]
    total = 0

    def _land(path, write_fn, mode):
        nonlocal total
        with open(path, mode) as f:
            write_fn(f)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        total += os.path.getsize(path)
        if on_file is not None:
            on_file(path)

    _land(os.path.join(directory, SHARDS.format(proc=proc)),
          lambda f: np.savez(f, **snapshot["arrays"]), "wb")
    _land(os.path.join(directory, MANIFEST.format(proc=proc)),
          lambda f: json.dump(snapshot["manifest"], f), "w")
    if snapshot["meta"] is not None:
        _land(os.path.join(directory, META),
              lambda f: json.dump(snapshot["meta"], f), "w")
    return total


def save_checkpoint(directory: str, net, *, trees: Optional[Dict[str, Any]] = None) -> None:
    """Write this process's shards of the facade's params / updater state /
    net state (or explicit ``trees``) plus iteration + RNG root key.

    NOTE: this low-level call writes straight into the live ``directory``
    — a crash mid-save leaves a torn checkpoint there.  Production runs
    should save through ``resilience.CheckpointManager``, which stages the
    same files in a ``step-N.tmp/`` directory and commits them atomically
    (tmp -> fsync -> rename + COMMIT manifest)."""
    snapshot = snapshot_trees(net, trees=trees)
    write_snapshot(directory, snapshot)
    from deeplearning4j_tpu.observability import get_flight_recorder

    get_flight_recorder().record(
        "checkpoint", directory=str(directory), process=snapshot["proc"],
        iteration=snapshot["iteration"])


# --------------------------------------------------------------------- restore
def _assemble(entry, shard_files) -> np.ndarray:
    shape = tuple(entry["shape"])
    dtype = np.dtype(entry["dtype"])
    out = np.zeros(shape, dtype)
    if not shape:  # scalar
        for npz in shard_files:
            for s in entry["shards"]:
                if s["key"] in npz:
                    return npz[s["key"]].astype(dtype)
    filled = np.zeros(shape, bool)
    for npz in shard_files:
        for s in entry["shards"]:
            if s["key"] not in npz:
                continue
            sl = tuple(slice(a, b) for a, b in s["index"])
            out[sl] = npz[s["key"]]
            filled[sl] = True
    if not bool(filled.all()):
        raise ValueError(
            f"checkpoint incomplete: leaf {entry} missing shard data "
            f"(multi-host checkpoint restored without shared storage?)")
    return out


def restore_checkpoint(directory: str, net=None, *, mesh: Optional[Mesh] = None
                       ) -> Tuple[Dict[str, Any], Dict[str, Any], Dict[str, Any], int]:
    """Reassemble (params, updater_state, net_state, iteration).  With
    ``net`` given, restores in place (incl. iteration + RNG stream).  With
    ``mesh`` given, leaves are placed with their saved PartitionSpec over
    that mesh; otherwise they come back as host-backed arrays."""
    manifests = []
    shard_files = []
    for fn in sorted(os.listdir(directory)):
        if fn.startswith("manifest-"):
            with open(os.path.join(directory, fn)) as f:
                manifests.append(json.load(f))
        elif fn.startswith("shards-"):
            shard_files.append(np.load(os.path.join(directory, fn)))
    if not manifests:
        raise FileNotFoundError(f"no checkpoint manifests in {directory}")
    # merge per-process manifests: each host recorded only its own shards of
    # a cross-host-sharded leaf, so a leaf's shard list is the UNION over
    # all manifests (shape/dtype/spec agree by construction)
    merged: Dict[str, Any] = {}
    for man in manifests:
        for path, entry in man["leaves"].items():
            if path not in merged:
                merged[path] = {k: entry[k] for k in ("shape", "dtype", "spec")}
                merged[path]["shards"] = list(entry["shards"])
            else:
                have = {s["key"] for s in merged[path]["shards"]}
                merged[path]["shards"] += [s for s in entry["shards"]
                                           if s["key"] not in have]
    leaves: Dict[str, Any] = {}
    for path, entry in merged.items():
        arr = _assemble(entry, shard_files)
        if mesh is not None:
            spec = _spec_from_json(entry["spec"])
            arr = jax.device_put(arr, NamedSharding(mesh, spec))
        else:
            arr = jnp.asarray(arr)
        leaves[path] = arr
    for npz in shard_files:
        npz.close()
    full = _unflatten(leaves)
    params = full.get("params", {})
    upd = full.get("updater_state", {})
    ns = full.get("net_state", {})
    with open(os.path.join(directory, META)) as f:
        meta = json.load(f)
    iteration = int(meta.get("iteration", 0))
    if net is not None:
        net.params = params
        net.updater_state = upd
        net.net_state = ns
        net.iteration = iteration
        if "rng_key" in meta and getattr(net, "_keys", None) is not None:
            net._keys._key = jax.random.wrap_key_data(
                jnp.asarray(np.asarray(meta["rng_key"], np.uint32)))
    return params, upd, ns, iteration
