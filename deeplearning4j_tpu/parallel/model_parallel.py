"""Tensor + expert parallelism — sharded-parameter training over the mesh.

Beyond-reference extension (SURVEY.md §2: TP/EP absent in the reference;
its only axis is data parallelism).  Idiomatic JAX: no communication code —
parameters get ``NamedSharding`` layouts over the mesh's model axis
(Megatron-style alternating column/row splits for dense chains, output
channels for convs, the expert axis for MoE), the batch shards over the
data axis, and GSPMD inserts the all-gathers/reduce-scatters so the
matmul partials ride ICI.

Composes dp x tp on one mesh: ``default_mesh(data=4, model=2)`` trains 4-way
data-parallel with every parameter split across 2 chips.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.parallel.training_master import SyncTrainingMaster


def tensor_parallel_spec(params: Dict[str, Dict[str, Any]], tp: int,
                         axis: str = backend.AXIS_MODEL) -> Dict[str, Dict[str, Any]]:
    """Per-parameter PartitionSpecs, recursive over nested param trees
    (composite layers like ResidualBlock nest sublayer dicts).

    Rules:
      - attention groups ({Wq, Wk, Wv, Wo} siblings): Megatron attention —
        Wq/Wk/Wv column-parallel (shards heads), Wo row-parallel, so the
        whole attention block needs one collective pair;
      - other 2-D weights: alternate column-parallel P(None, axis) /
        row-parallel P(axis, None) in traversal order — back-to-back dense
        layers then need a single collective pair per block (Megatron MLP);
      - 4-D conv kernels [kh,kw,cin,cout]: shard cout;
      - 3-D expert tensors [E,...]: shard the expert axis (EP);
      - biases/vectors and anything not divisible by tp: replicated.
    """
    parity = [0]

    def leaf_spec(pname, arr, attn, par):
        nd = getattr(arr, "ndim", 0)
        shape = getattr(arr, "shape", ())
        if nd == 2 and pname.startswith("W"):
            if attn:
                if pname in ("Wq", "Wk", "Wv") and shape[1] % tp == 0:
                    return P(None, axis), True
                if pname == "Wo" and shape[0] % tp == 0:
                    return P(axis, None), True
                return P(), True
            if par % 2 == 0 and shape[1] % tp == 0:
                return P(None, axis), True
            if par % 2 == 1 and shape[0] % tp == 0:
                return P(axis, None), True
            return P(), True
        if nd == 4 and shape and shape[-1] % tp == 0:
            return P(None, None, None, axis), True         # conv cout
        if nd == 3 and shape and shape[0] % tp == 0:
            return P(axis, None, None), True               # MoE experts
        return P(), False

    def walk(tree):
        out = {}
        keys = set(tree.keys())
        attn = {"Wq", "Wk", "Wv", "Wo"} <= keys
        saw = False
        for pname, v in tree.items():
            if isinstance(v, dict):
                out[pname] = walk(v)
            else:
                spec, matrix = leaf_spec(pname, v, attn, parity[0])
                out[pname] = spec
                saw = saw or (matrix and not attn)
        if attn:
            # the attention group is a complete col->row stage; snap parity
            # to the next EVEN value so the following FFN starts
            # column-parallel (one collective pair per block)
            parity[0] = (parity[0] // 2 + 1) * 2
        elif saw:
            parity[0] += 1
        return out

    return {lname: walk(lparams) for lname, lparams in params.items()}


class TensorParallelTrainingMaster(SyncTrainingMaster):
    """SyncTrainingMaster whose parameters live sharded over the model axis.

    The jitted step is identical to plain DP — the difference is entirely
    in data placement: params/updater-state are device_put with the
    tensor-parallel NamedShardings and jit propagates them (GSPMD), so
    forward/backward matmuls compute on parameter shards and the gradient
    all-reduce over the data axis coexists with the TP collectives.
    """

    def __init__(self, mesh: Optional[Mesh] = None, **kw):
        super().__init__(mesh=mesh or backend.default_mesh(), **kw)
        if backend.AXIS_MODEL not in self.mesh.shape:
            raise ValueError("mesh needs a model axis (default_mesh(model=N))")
        self.tp = self.mesh.shape[backend.AXIS_MODEL]

    def _param_layout(self, net):
        specs = tensor_parallel_spec(net.params, self.tp)

        def to_shardings(tree):
            return {
                k: (to_shardings(v) if isinstance(v, dict)
                    else NamedSharding(self.mesh, v))
                for k, v in tree.items()
            }

        return to_shardings(specs)
