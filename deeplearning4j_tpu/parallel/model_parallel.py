"""Tensor + expert parallelism — sharded-parameter training over the mesh.

Beyond-reference extension (SURVEY.md §2: TP/EP absent in the reference;
its only axis is data parallelism).  Idiomatic JAX: no communication code —
parameters get ``NamedSharding`` layouts over the mesh's model axis
(Megatron-style alternating column/row splits for dense chains, output
channels for convs, the expert axis for MoE), the batch shards over the
data axis, and GSPMD inserts the all-gathers/reduce-scatters so the
matmul partials ride ICI.

Composes dp x tp on one mesh: ``default_mesh(data=4, model=2)`` trains 4-way
data-parallel with every parameter split across 2 chips.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.parallel.training_master import SyncTrainingMaster


def tensor_parallel_spec(params: Dict[str, Dict[str, Any]], tp: int,
                         axis: str = backend.AXIS_MODEL) -> Dict[str, Dict[str, P]]:
    """Per-parameter PartitionSpecs.

    Rules (layer order = alternation order):
      - 2-D weights: alternate column-parallel P(None, axis) / row-parallel
        P(axis, None) down the layer stack — back-to-back dense layers then
        need a single collective pair per block (Megatron MLP pattern);
      - 4-D conv kernels [kh,kw,cin,cout]: shard cout;
      - 3-D expert tensors [E,...]: shard the expert axis (EP);
      - biases/vectors and anything not divisible by tp: replicated.
    """
    specs: Dict[str, Dict[str, P]] = {}
    parity = 0
    for lname, lparams in params.items():
        lspec: Dict[str, P] = {}
        saw_matrix = False
        for pname, arr in lparams.items():
            nd = getattr(arr, "ndim", 0)
            shape = getattr(arr, "shape", ())
            if nd == 2 and pname.startswith("W"):
                if parity % 2 == 0 and shape[1] % tp == 0:
                    lspec[pname] = P(None, axis)
                elif parity % 2 == 1 and shape[0] % tp == 0:
                    lspec[pname] = P(axis, None)
                else:
                    lspec[pname] = P()
                saw_matrix = True
            elif nd == 4 and shape[-1] % tp == 0:
                lspec[pname] = P(None, None, None, axis)   # conv cout
                saw_matrix = True
            elif nd == 3 and shape[0] % tp == 0:
                lspec[pname] = P(axis, None, None)         # MoE experts
                saw_matrix = True
            else:
                lspec[pname] = P()
        specs[lname] = lspec
        if saw_matrix:
            parity += 1
    return specs


class TensorParallelTrainingMaster(SyncTrainingMaster):
    """SyncTrainingMaster whose parameters live sharded over the model axis.

    The jitted step is identical to plain DP — the difference is entirely
    in data placement: params/updater-state are device_put with the
    tensor-parallel NamedShardings and jit propagates them (GSPMD), so
    forward/backward matmuls compute on parameter shards and the gradient
    all-reduce over the data axis coexists with the TP collectives.
    """

    def __init__(self, mesh: Optional[Mesh] = None, **kw):
        super().__init__(mesh=mesh or backend.default_mesh(), **kw)
        if backend.AXIS_MODEL not in self.mesh.shape:
            raise ValueError("mesh needs a model axis (default_mesh(model=N))")
        self.tp = self.mesh.shape[backend.AXIS_MODEL]

    def _param_layout(self, net):
        specs = tensor_parallel_spec(net.params, self.tp)
        return {
            ln: {pn: NamedSharding(self.mesh, s) for pn, s in lp.items()}
            for ln, lp in specs.items()
        }
