"""Elastic data parallelism: degraded-mode eviction and re-admission.

DeepSpark (arXiv 1602.08191) observes that a synchronous averaging window
runs at the speed of its slowest replica, and that relaxing synchrony over
a *degraded worker set* — drop the straggler from the collective,
renormalize the average over the healthy replicas, keep going — preserves
convergence while restoring throughput.  This module is that protocol for
the single-program mesh world of ``ParallelWrapper`` /
``SyncTrainingMaster``:

- **eviction** is a *mask*, not a topology change: the K-replica vmapped
  window program is compiled once, and an evicted replica is excluded by
  a runtime ``[K]`` weight vector — the parameter/updater average is
  renormalized over the healthy set (``sum(w*x)/sum(w)``), so the XLA
  shape set stays closed and eviction costs zero recompiles;
- **verdicts** come from three deterministic sources, polled once per
  window boundary: the ``StragglerDetector`` (a replica flagged
  ``evict_after_flags`` times since admission), a per-worker fault signal
  (``FaultInjector.hang_worker`` — the worker stopped responding), and
  worker death (``FaultInjector.kill_worker`` — per-worker SIGTERM /
  preempted host);
- **re-admission** happens at a window boundary after the fault clears
  (hang/death) or after ``readmit_after_windows`` of quarantine
  (straggler probation).  Catch-up is checkpoint-fed by construction:
  every window broadcasts the renormalized healthy average into *all* K
  slots — evicted ones included — so the returning replica's slot already
  holds the current averaged params the moment its weight flips back to
  1.  A re-admitted straggler starts a fresh flag budget; if it is still
  slow it is simply evicted again;
- the **synchrony barrier simulation** makes the cost model honest on the
  virtual-device test tier: with a ``FaultInjector`` active, each window
  stalls for the slowest ACTIVE worker's injected delay (lockstep
  semantics — what a real mesh pays in ICI wait).  Degraded mode's win is
  exactly the stall it no longer pays; ``bench_elastic`` measures it.

Every transition lands in the flight recorder (``elastic_eviction`` /
``elastic_readmission`` events naming the replica) and the
``dl4j_elastic_*`` metric families, and the ``max_evicted_replicas``
health rule (observability.health) turns a too-degraded mesh into a
failing ``/health``.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

_EVICTIONS = "dl4j_elastic_evictions_total"
_READMISSIONS = "dl4j_elastic_readmissions_total"
_ACTIVE = "dl4j_elastic_active_replicas"
_EVICTED = "dl4j_elastic_evicted_replicas"
_DEGRADED = "dl4j_elastic_degraded_windows_total"
_STALL = "dl4j_elastic_window_stall_seconds"
_REFUSALS = "dl4j_elastic_eviction_refusals_total"


class ElasticConfig:
    """Tuning for one component's ``ElasticController``.

    ``degraded_mode`` — master switch: off keeps full lockstep semantics
    (no evictions ever; the barrier simulation still stalls on every
    worker — this is the "today's behavior" arm of ``bench_elastic``).
    ``evict_after_flags`` — straggler verdicts (detector flags since
    admission) that trigger eviction; ``None`` disables straggler-based
    eviction (hang/death still evict).  ``min_healthy`` — never evict
    below this many active replicas.  ``max_evicted`` — cap on
    simultaneously evicted replicas (default ``K - min_healthy``); the
    ``max_evicted_replicas`` health rule typically mirrors it.
    ``readmit_after_windows`` — quarantine length before a straggler
    eviction is probationally re-admitted.  The ``straggler_*`` fields
    parameterize the detector the wrapper builds when elasticity is on
    (``min_steps`` low so verdicts arrive within a few windows).
    ``hang_stall_s`` — what the barrier simulation charges per window for
    an ACTIVE hung worker (a stand-in for a watchdog timeout; evicting is
    the fix).
    """

    def __init__(self, degraded_mode: bool = True,
                 evict_after_flags: Optional[int] = 2,
                 min_healthy: int = 1,
                 max_evicted: Optional[int] = None,
                 readmit_after_windows: int = 16,
                 straggler_threshold: float = 2.0,
                 straggler_window: int = 32,
                 straggler_min_steps: int = 2,
                 straggler_min_excess_s: float = 0.010,
                 hang_stall_s: float = 0.05):
        if min_healthy < 1:
            raise ValueError(f"min_healthy must be >= 1, got {min_healthy}")
        self.degraded_mode = bool(degraded_mode)
        self.evict_after_flags = evict_after_flags
        self.min_healthy = int(min_healthy)
        self.max_evicted = max_evicted
        self.readmit_after_windows = int(readmit_after_windows)
        self.straggler_threshold = float(straggler_threshold)
        self.straggler_window = int(straggler_window)
        self.straggler_min_steps = int(straggler_min_steps)
        self.straggler_min_excess_s = float(straggler_min_excess_s)
        self.hang_stall_s = float(hang_stall_s)

    def make_worker_telemetry(self, component: str):
        """The per-worker telemetry parameterized by this config's
        ``straggler_*`` fields — the single construction point shared by
        ``ParallelWrapper`` and ``SyncTrainingMaster``, so a new tuning
        field cannot silently diverge between the two masters."""
        from deeplearning4j_tpu.observability import WorkerTelemetry

        return WorkerTelemetry(
            component,
            threshold=self.straggler_threshold,
            window=self.straggler_window,
            min_steps=self.straggler_min_steps,
            min_excess_s=self.straggler_min_excess_s)


class ElasticController:
    """Per-fit elasticity state machine for one component (module
    docstring).  ``worker_ids`` fixes the replica naming the component
    already publishes telemetry under (``"0".."K-1"`` for the wrapper,
    ``"d<id>"`` for the sync master), so detector verdicts, injected
    faults, and eviction events all name the same replica."""

    def __init__(self, component: str, worker_ids: List[str], *,
                 config: Optional[ElasticConfig] = None,
                 detector=None, registry=None,
                 aliases: Optional[Dict[str, List[str]]] = None):
        self.component = component
        self.workers = [str(w) for w in worker_ids]
        self.K = len(self.workers)
        self.cfg = config or ElasticConfig()
        self.detector = detector       # attached by the wrapper once built
        # aliases: every device id a worker slot answers for.  On a
        # data x model mesh one DATA slot spans several devices; a fault
        # or straggler verdict on ANY of them must evict the whole slot
        # (the collective is gated by the slot's slowest member).
        aliases = aliases or {}
        self.aliases: Dict[str, List[str]] = {
            w: [str(a) for a in aliases.get(w, (w,))] for w in self.workers
        }
        if registry is None:
            from deeplearning4j_tpu.observability import get_registry
            registry = get_registry()
        self._m_evictions = registry.counter(
            _EVICTIONS, "Replica evictions from the data-parallel "
            "collective, by reason (straggler / hang / dead / poisoned / "
            "manual) — the evicted replica is named in the worker label",
            labels=("component", "worker", "reason"))
        self._m_readmissions = registry.counter(
            _READMISSIONS, "Replica re-admissions into the collective "
            "after catch-up (broadcast of the averaged params at a window "
            "boundary)", labels=("component", "worker"))
        self._m_active = registry.gauge(
            _ACTIVE, "Replicas currently participating in the averaging "
            "collective", labels=("component",))
        self._m_evicted = registry.gauge(
            _EVICTED, "Replicas currently evicted from the averaging "
            "collective (read by the max_evicted_replicas health rule)",
            labels=("component",))
        self._m_degraded = registry.counter(
            _DEGRADED, "Averaging windows executed with at least one "
            "replica evicted (renormalized over the healthy set)",
            labels=("component",))
        self._m_stall = registry.histogram(
            _STALL, "Synchrony-barrier stall charged per window by the "
            "slowest ACTIVE worker (fault-injection simulation of the "
            "lockstep ICI wait)", labels=("component",))
        self._m_refusals = registry.counter(
            _REFUSALS, "Evictions refused by the min_healthy/max_evicted "
            "caps — the faulty replica is STILL in the averaging "
            "collective; one increment per refused (worker, reason) "
            "episode", labels=("component", "worker", "reason"))
        self._state: Dict[str, Dict[str, Any]] = {
            w: {"active": True, "reason": None, "since": None,
                "windows_out": 0, "flag_base": 0, "refused": None}
            for w in self.workers
        }
        self._publish_gauges()

    # ------------------------------------------------------------- queries
    @property
    def active_workers(self) -> List[str]:
        return [w for w in self.workers if self._state[w]["active"]]

    @property
    def evicted_workers(self) -> List[str]:
        return [w for w in self.workers if not self._state[w]["active"]]

    def active_mask(self) -> np.ndarray:
        return np.asarray(
            [1.0 if self._state[w]["active"] else 0.0 for w in self.workers],
            np.float32)

    def summary(self) -> Dict[str, Any]:
        return {
            "component": self.component,
            "workers": self.K,
            "active": len(self.active_workers),
            "evicted": {w: {"reason": self._state[w]["reason"],
                            "since_step": self._state[w]["since"],
                            "windows_out": self._state[w]["windows_out"]}
                        for w in self.evicted_workers},
        }

    # --------------------------------------------------------- transitions
    def _publish_gauges(self) -> None:
        n_active = len(self.active_workers)
        self._m_active.set(float(n_active), component=self.component)
        self._m_evicted.set(float(self.K - n_active),
                            component=self.component)

    def _max_evicted(self) -> int:
        if self.cfg.max_evicted is not None:
            return min(int(self.cfg.max_evicted),
                       self.K - self.cfg.min_healthy)
        return self.K - self.cfg.min_healthy

    def evict(self, worker, reason: str, step: int) -> bool:
        """Evict ``worker`` at the next window boundary; refused (False)
        when degraded mode is off (lockstep semantics admit no evictions,
        manual or otherwise), when it would leave fewer than
        ``min_healthy`` active replicas, or when it would exceed
        ``max_evicted``."""
        from deeplearning4j_tpu.observability import get_flight_recorder

        if not self.cfg.degraded_mode:
            return False
        worker = str(worker)
        st = self._state[worker]
        if not st["active"]:
            return True
        if (len(self.active_workers) - 1 < self.cfg.min_healthy
                or len(self.evicted_workers) + 1 > self._max_evicted()):
            return False
        st.update(active=False, reason=reason, since=int(step),
                  windows_out=0, refused=None)
        self._m_evictions.inc(component=self.component, worker=worker,
                              reason=reason)
        self._publish_gauges()
        get_flight_recorder().record(
            "elastic_eviction", component=self.component, worker=worker,
            reason=reason, step=int(step),
            active=len(self.active_workers))
        return True

    def readmit(self, worker, step: int) -> None:
        """Re-admit ``worker`` at a window boundary.  Its slot already
        holds the current averaged params (every window broadcasts the
        healthy average into all K slots), so no further catch-up is
        needed; its straggler flag budget restarts from now."""
        from deeplearning4j_tpu.observability import get_flight_recorder

        worker = str(worker)
        st = self._state[worker]
        if st["active"]:
            return
        st.update(active=True, reason=None, since=None, windows_out=0,
                  flag_base=self._flags(worker), refused=None)
        self._m_readmissions.inc(component=self.component, worker=worker)
        self._publish_gauges()
        get_flight_recorder().record(
            "elastic_readmission", component=self.component, worker=worker,
            step=int(step), active=len(self.active_workers))

    def attach_detector(self, detector) -> None:
        """Point verdicts at ``detector``, rebasing every worker's flag
        budget on its current counts.  A controller that outlives one fit
        (``ParameterAveragingTrainingMaster`` re-wraps per epoch) gets a
        fresh ``StragglerDetector`` each time; without the rebase, stale
        ``flag_base`` values from the previous detector would demand
        ``base + evict_after_flags`` flags before the next eviction."""
        if detector is self.detector:
            return
        self.detector = detector
        for w in self.workers:
            self._state[w]["flag_base"] = self._flags(w)

    def _evict_or_report(self, worker: str, reason: str, step: int) -> None:
        """Evict, or make the refusal VISIBLE: a dead/hung/straggling
        replica the caps keep in the collective is the worst degraded
        state — without this, the evicted-replicas gauge and the
        max_evicted_replicas health rule both read healthy while garbage
        params keep entering the average.  One metric increment + flight
        event per (worker, reason) episode, re-armed when the fault
        clears or the eviction finally lands."""
        from deeplearning4j_tpu.observability import get_flight_recorder

        st = self._state[worker]
        if self.evict(worker, reason, step):
            return
        if st["refused"] == reason:
            return                      # already reported this episode
        st["refused"] = reason
        self._m_refusals.inc(component=self.component, worker=worker,
                             reason=reason)
        get_flight_recorder().record(
            "elastic_eviction_refused", component=self.component,
            worker=worker, reason=reason, step=int(step),
            active=len(self.active_workers),
            min_healthy=self.cfg.min_healthy,
            max_evicted=self._max_evicted())

    def report_poisoned(self, worker, step: int) -> None:
        """Device-side repeat-offender verdict from the stability engine
        (``resilience/stability.py``): the named replica's gradients were
        non-finite in ``poison_evict_after``+ averaging windows — evict
        it with reason ``"poisoned"`` (or make the cap refusal visible).
        Re-admission follows the straggler probation path once the fault
        clears."""
        worker = str(worker)
        if not self._state[worker]["active"]:
            return
        self._evict_or_report(worker, "poisoned", step)

    def _flags(self, worker: str) -> int:
        if self.detector is None:
            return 0
        flags = self.detector.stragglers()
        return sum(flags.get(a, 0) for a in self.aliases[worker])

    def _worker_fault(self, inj, worker: str, step: int) -> str:
        """Worst injected state over the slot's member devices
        (``dead`` > ``hung`` > ``poisoned`` > ``ok``)."""
        if inj is None:
            return "ok"
        rank = {"ok": 0, "poisoned": 1, "hung": 2, "dead": 3}
        state = "ok"
        for a in self.aliases[worker]:
            s = inj.worker_state(a, step)
            if s == "dead":
                return "dead"
            if rank.get(s, 0) > rank[state]:
                state = s
        return state

    # ------------------------------------------------------ window protocol
    def begin_window(self, step: int) -> np.ndarray:
        """Poll verdict sources and apply due transitions; returns the
        ``[K]`` float mask for this window (all ones when degraded mode is
        off or the mesh is healthy)."""
        from deeplearning4j_tpu.resilience import get_fault_injector

        inj = get_fault_injector()
        if self.cfg.degraded_mode:
            for w in self.workers:
                st = self._state[w]
                fault = self._worker_fault(inj, w, step)
                if st["active"]:
                    if fault == "dead":
                        self._evict_or_report(w, "dead", step)
                    elif fault == "hung":
                        self._evict_or_report(w, "hang", step)
                    elif (self.cfg.evict_after_flags is not None
                          and self._flags(w) - st["flag_base"]
                          >= self.cfg.evict_after_flags):
                        self._evict_or_report(w, "straggler", step)
                    elif fault == "ok":
                        st["refused"] = None   # episode over: fault gone
                    # fault == "poisoned": an ACTIVE poisoned replica is
                    # handled device-side (its gradients are weighted out
                    # of the average per window); eviction arrives via
                    # report_poisoned once it is a repeat offender
                else:
                    st["windows_out"] += 1
                    if fault != "ok":
                        continue       # fault still live: stay evicted
                    if st["reason"] in ("dead", "hang"):
                        self.readmit(w, step)   # fault cleared
                    elif (st["reason"] in ("straggler", "poisoned")
                          and st["windows_out"]
                          >= self.cfg.readmit_after_windows):
                        # probation: a straggler verdict or poison streak
                        # may have been transient (bad data window) — the
                        # next offense just re-evicts
                        self.readmit(w, step)
                    # any other reason (e.g. "manual") stays evicted until
                    # an explicit readmit() — an operator decision is not
                    # a fault that clears or a verdict that expires
        mask = self.active_mask()
        if mask.sum() < self.K:
            self._m_degraded.inc(component=self.component)
        return mask

    def window_barrier(self, step: int) -> float:
        """Synchrony-barrier simulation: stall this window by the slowest
        ACTIVE worker's injected delay (plus ``hang_stall_s`` for an
        active hung worker).  A no-op without a ``FaultInjector`` — real
        hardware pays this wait inside the collective, not here."""
        from deeplearning4j_tpu.resilience import get_fault_injector

        inj = get_fault_injector()
        if inj is None:
            return 0.0
        stall = 0.0
        for w in self.active_workers:
            d = max(inj.worker_delay(a) for a in self.aliases[w])
            if self._worker_fault(inj, w, step) != "ok":
                d = max(d, self.cfg.hang_stall_s)
            stall = max(stall, d)
        if stall > 0.0:
            time.sleep(stall)
            self._m_stall.observe(stall, component=self.component)
        return stall
