from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.training_master import (
    TrainingMaster,
    SyncTrainingMaster,
    ParameterAveragingTrainingMaster,
    DistributedNetwork,
)
