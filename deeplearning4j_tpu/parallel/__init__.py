from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.elastic import (
    ElasticConfig,
    ElasticController,
)
from deeplearning4j_tpu.parallel.training_master import (
    TrainingMaster,
    SyncTrainingMaster,
    ParameterAveragingTrainingMaster,
    DistributedNetwork,
)
from deeplearning4j_tpu.parallel.sequence_parallel import (
    SequenceParallelTrainingMaster,
    ring_attention,
    ring_self_attention,
    ulysses_attention,
)
from deeplearning4j_tpu.parallel.model_parallel import (
    TensorParallelTrainingMaster,
    tensor_parallel_spec,
)
from deeplearning4j_tpu.parallel.pipeline import (
    PipelineParallelTrainingMaster,
    split_stages,
)
from deeplearning4j_tpu.parallel.checkpoint import (
    save_checkpoint,
    restore_checkpoint,
    snapshot_trees,
    write_snapshot,
)
