"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence parallelism — long sequences are handled by
truncated BPTT and masking only (SURVEY.md §5; reference
``MultiLayerNetwork.java:1176``).  For the TPU framework long context is
first-class: the sequence axis is a mesh axis (``backend.AXIS_SEQ``), each
chip holds a contiguous time shard, and attention runs either as

- **ring attention** (`ring_attention`): K/V blocks rotate around the ring
  of sequence shards via ``lax.ppermute`` while each chip folds one block
  per step into an online-softmax accumulator (blockwise/flash-style
  numerically stable rescaling).  Communication is neighbor-only, so it
  rides ICI at O(T/P) memory per chip — never materializing the [T, T]
  score matrix or an all-gathered K/V.
- **Ulysses attention** (`ulysses_attention`): two ``lax.all_to_all``s
  reshard [B, T/P, H, D] -> [B, T, H/P, D], run exact local attention per
  head group, and reshard back.  Cheaper for moderate T with many heads.

``SequenceParallelTrainingMaster`` jits a FULL training step under
``shard_map`` over (data, seq): batch sharded over 'data', time sharded over
'seq', params replicated, gradients pmean'd over both axes.  Equivalence to
single-device training is the correctness contract (tests mirror the
reference's distributed-vs-local pattern,
``TestCompareParameterAveragingSparkVsSingleMachine``).
"""

from __future__ import annotations

import collections
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.backend.compat import pcast, shard_map

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.optimize import updaters as upd

_NEG = -1e30


def ring_attention(q, k, v, mask=None, *, axis_name: str,
                   causal: bool = False, window: Optional[int] = None):
    """Blockwise ring attention over one mesh axis.

    Must be called inside ``shard_map``; ``q/k/v`` are local sequence shards
    of shape [B, T_local, H, D] (shard i holds global timesteps
    ``[i*T_local, (i+1)*T_local)``); ``mask`` is the local [B, T_local]
    key-padding shard and rotates around the ring with K/V.  Returns the
    local shard of the exact attention output — numerically identical (up to
    fp associativity) to full attention on the gathered sequence.
    """
    from deeplearning4j_tpu.nn.layers.attention import check_window

    check_window(causal, window)
    n_shards = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    hkv = k.shape[2]
    grouped = hkv != h
    if grouped and h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    groups = h // hkv
    q_off = idx * t_local
    qpos = q_off + jnp.arange(t_local)

    # online-softmax accumulators in >=f32; pcast marks them as varying
    # over the ring axis so the scan carry typechecks under shard_map
    acc = jnp.promote_types(q.dtype, jnp.float32)
    qf = q.astype(acc)
    o0 = pcast(jnp.zeros((b, h, t_local, d), acc), (axis_name,), to="varying")
    l0 = pcast(jnp.zeros((b, h, t_local), acc), (axis_name,), to="varying")
    m0 = pcast(jnp.full((b, h, t_local), _NEG, acc), (axis_name,), to="varying")
    scale = jnp.asarray(1.0 / np.sqrt(d), acc)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def fold_block(o, l, m, k_cur, v_cur, mask_cur, s):
        """Online-softmax fold of the K/V block currently held (block s of
        the rotation; globally it is shard (idx - s) mod n_shards)."""
        src = (idx - s) % n_shards
        kpos = src * t_local + jnp.arange(t_local)
        if grouped:
            # GQA: contract each KV head against its query-head group
            # directly — the rotating K/V stays at H_kv heads, so ICI
            # traffic and per-chip K/V memory keep the GQA shrink.
            # (hkv, g) flattens in the same head order as jnp.repeat.
            qg = qf.reshape(b, t_local, hkv, groups, d)
            scores = jnp.einsum(
                "bqhgd,bkhd->bhgqk", qg, k_cur.astype(acc)
            ).reshape(b, h, t_local, t_local) * scale
        else:
            scores = jnp.einsum(
                "bqhd,bkhd->bhqk", qf, k_cur.astype(acc)) * scale
        if causal:
            blk_mask = qpos[:, None] >= kpos[None, :]       # [Tq, Tk]
            if window is not None:
                # sliding window by GLOBAL position, same band as the
                # local paths: kpos in [qpos - window + 1, qpos]
                blk_mask &= kpos[None, :] > qpos[:, None] - window
            valid = blk_mask[None, None]
        else:
            valid = jnp.ones((1, 1, t_local, t_local), bool)
        if mask_cur is not None:
            valid = valid & mask_cur.astype(bool)[:, None, None, :]
        scores = jnp.where(valid, scores, _NEG)
        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        p = jnp.where(valid, jnp.exp(scores - m_new[..., None]), 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        if grouped:
            pg = p.reshape(b, hkv, groups, t_local, t_local)
            pv = jnp.einsum("bhgqk,bkhd->bhgqd", pg, v_cur.astype(acc)
                            ).reshape(b, h, t_local, d)
        else:
            pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_cur.astype(acc))
        o = o * alpha[..., None] + pv
        return o, l, m_new

    # step 0 folds the local block with no communication; remaining steps
    # rotate FIRST then fold, so no ppermute result is ever discarded
    o, l, m = fold_block(o0, l0, m0, k, v, mask, 0)

    def body(carry, s):
        o, l, m, k_cur, v_cur, mask_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        if mask_cur is not None:
            mask_cur = lax.ppermute(mask_cur, axis_name, perm)
        o, l, m = fold_block(o, l, m, k_cur, v_cur, mask_cur, s)
        return (o, l, m, k_cur, v_cur, mask_cur), None

    if n_shards > 1:
        (o, l, m, _, _, _), _ = lax.scan(
            body, (o, l, m, k, v, mask), jnp.arange(1, n_shards))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)   # [B,T,H,D]


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = False,
                      window: Optional[int] = None):
    """DeepSpeed-Ulysses-style all-to-all sequence parallelism.

    Inside ``shard_map``: reshard time-sharded heads to head-sharded full
    sequence, run exact local attention, reshard back.  Requires
    ``H % n_shards == 0``.
    """
    from deeplearning4j_tpu.helpers import get_helper
    from deeplearning4j_tpu.nn.layers.attention import (
        check_window, dot_product_attention,
    )

    check_window(causal, window)
    n_shards = lax.psum(1, axis_name)

    def to_heads(x):   # [B, T/P, H, D] -> [B, T, H/P, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    # after resharding the local attention is exact full-T — route it
    # through the flash helper seam as SelfAttentionLayer does; the helper
    # owns the policy (under shard_map only the compiled path qualifies)
    helper = get_helper("attention")
    # flash helper is MHA-only (its to_bh reshape assumes k/v share q's
    # head count) — GQA (H_kv < H) must take the grouped einsum path
    if (helper is not None and qh.dtype != jnp.float64
            and kh.shape[2] == qh.shape[2]
            and helper.supports(qh.shape[1], qh.shape[3],
                                under_shard_map=True)):
        o = helper.attend(qh, kh, vh, causal=causal, window=window)
    else:
        o = dot_product_attention(qh, kh, vh, causal=causal, window=window)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ring_self_attention(q, k, v, mesh: Optional[Mesh] = None, *,
                        causal: bool = False, window: Optional[int] = None,
                        impl: str = "ring",
                        seq_axis: str = backend.AXIS_SEQ):
    """Convenience wrapper: global [B, T, H, D] arrays in, attention over a
    sequence-sharded mesh, global-layout result out (still sharded)."""
    mesh = mesh or backend.default_mesh()
    fn = ring_attention if impl == "ring" else ulysses_attention
    spec = P(None, seq_axis)
    return shard_map(
        functools.partial(fn, axis_name=seq_axis, causal=causal,
                          window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)


class SequenceParallelTrainingMaster:
    """Train with batch sharded over 'data' AND time sharded over 'seq'.

    Supported nets: Sequential stacks whose layers are timestep-local
    (Embedding/Dense/LayerNorm/Activation/RnnOutput) plus
    ``SelfAttentionLayer(seq_axis='seq')`` — i.e. transformer LMs.  Recurrent
    scan layers (LSTM) carry state across time shards and are NOT supported
    here; use TBPTT for those (reference parity path).

    The whole step is ONE ``shard_map``-ped XLA program: local forward/
    backward on [B/Kd, T/Ks] shards, ring collectives inside attention,
    one pmean of loss+grads over (data, seq) — no host round-trips.
    """

    def __init__(self, mesh: Optional[Mesh] = None, collect_stats: bool = False):
        self.mesh = mesh or backend.default_mesh()
        self.collect_stats = collect_stats
        # bounded window (last 1024): O(1) memory over long runs
        self._stats: Dict[str, Any] = {
            "steps": 0, "step_time_ms": collections.deque(maxlen=1024)}
        self._step = None

    def _build(self, net):
        cfg = net.conf.updater
        lr_overrides = {
            l.name: l.learning_rate for l in net.layers if l.learning_rate is not None
        }
        mesh = self.mesh
        axes = (backend.AXIS_DATA, backend.AXIS_SEQ)
        repl = P()
        data_seq = P(backend.AXIS_DATA, backend.AXIS_SEQ)

        ks = mesh.shape[backend.AXIS_SEQ]
        reg_layers = [l for l in net.layers if l.has_params()]

        def local_loss(params, net_state, x, y, rng):
            """Loss convention (reference, losses.score): per-example SUM over
            time, MEAN over batch.  Each seq shard's data term is a partial
            time-sum -> psum over 'seq' reassembles it; the replicated reg
            term must count ONCE, so scale it to reg/ks before the psum."""
            full, aux = net._loss_fn(params, net_state, x, y, rng)
            reg = jnp.zeros(())
            for l in reg_layers:
                reg = reg + l.reg_score(params[l.name])
            return full - reg * (1.0 - 1.0 / ks), aux

        def step(params, upd_state, net_state, iteration, x, y, rng):
            # distinct dropout streams per shard
            rng = jax.random.fold_in(rng, lax.axis_index(backend.AXIS_DATA))
            rng = jax.random.fold_in(rng, lax.axis_index(backend.AXIS_SEQ))
            (loss, (new_ns, _)), grads = jax.value_and_grad(
                local_loss, has_aux=True)(params, net_state, x, y, rng)
            # time-sum across seq shards, example-mean across data shards
            loss = lax.pmean(lax.psum(loss, backend.AXIS_SEQ), backend.AXIS_DATA)
            grads = {k2: v for k2, v in grads.items() if v}
            grads = lax.pmean(lax.psum(grads, backend.AXIS_SEQ), backend.AXIS_DATA)
            new_ns = lax.pmean(new_ns, axes) if new_ns else new_ns
            updates, new_us = upd.update(cfg, grads, upd_state, iteration,
                                         lr_overrides, params=params)
            new_params = {
                ln: (upd.apply_updates(params[ln], u)
                     if (u := updates.get(ln)) else params[ln])
                for ln in params
            }
            return new_params, new_us, new_ns, loss

        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(repl, repl, repl, repl, data_seq, data_seq, repl),
            out_specs=(repl, repl, repl, repl),
            check_vma=False,
        )
        self._step = jax.jit(sharded, donate_argnums=(0, 1, 2))
        self._data_sharding = NamedSharding(mesh, data_seq)
        self._repl_sharding = NamedSharding(mesh, repl)

    def execute_training(self, net, iterator):
        import time

        if self._step is None:
            self._build(net)
        params = jax.device_put(net.params, self._repl_sharding)
        upd_state = jax.device_put(net.updater_state, self._repl_sharding)
        ns = jax.device_put(net.net_state, self._repl_sharding)
        kd = self.mesh.shape[backend.AXIS_DATA]
        ks = self.mesh.shape[backend.AXIS_SEQ]
        for ds in iterator:
            # dl4jlint: disable-next-line=host-sync-in-hot-path -- iterator yields host numpy; asarray is a view, the device transfer is the explicit device_put below
            x, y = np.asarray(ds.features), np.asarray(ds.labels)
            if x.shape[0] % kd or x.shape[1] % ks:
                raise ValueError(
                    f"batch {x.shape[0]} / time {x.shape[1]} must divide mesh "
                    f"(data={kd}, seq={ks})")
            t0 = time.perf_counter()
            xj = jax.device_put(jnp.asarray(x), self._data_sharding)
            yj = jax.device_put(jnp.asarray(y), self._data_sharding)
            params, upd_state, ns, loss = self._step(
                params, upd_state, ns, jnp.asarray(float(net.iteration)),
                xj, yj, net._keys.next())
            net.score_value = loss  # device scalar; fetched lazily on read
            net.iteration += 1
            if self.collect_stats:
                self._stats["step_time_ms"].append((time.perf_counter() - t0) * 1e3)
            self._stats["steps"] += 1
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration)
        net.params, net.updater_state, net.net_state = params, upd_state, ns

    def training_stats(self):
        out = dict(self._stats)
        out["step_time_ms"] = list(out["step_time_ms"])
        return out
