"""ZeRO-style cross-replica sharding of the weight update.

Reference: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv 2004.13336) — at production model sizes
the Adam moments alone triple per-chip memory, yet every replica of a
data-parallel run stores and applies the SAME weight update.  The paper's
decomposition: reduce-scatter the gradients, let each replica update only
its 1/K shard of the parameters and optimizer state, all-gather the
parameters for the next forward.  Wire cost is identical to the
all-reduce it replaces (ring: 2(K-1)/K · bytes, split as (K-1)/K
reduce-scatter + (K-1)/K all-gather) and the persistent optimizer state
drops from K copies to one.

This module is the shared substrate both masters' ``update_sharding=
"zero"`` modes build on (``SyncTrainingMaster`` / ``ParallelWrapper``):

- **ZeroLayout** — the per-leaf sharding decision.  A leaf participates
  when its leading dimension divides the data-axis size
  (``shardstats.zero_shardable`` — the ONE owner of the predicate, so
  the ledger's projected-ZeRO column and the actual layout can be held
  to each other); non-dividing leaves and the reserved
  ``__stability__`` / ``__introspect__`` updater subtrees stay
  replicated, and the choice is recorded in the sharding ledger's
  ``notes``.
- **Collective helpers** used INSIDE the masters' ``shard_map`` blocks:
  ``all_gather_tree`` (sharded params -> full, the pre-forward gather),
  ``reduce_scatter_tree`` (summed gradient contributions -> shards; the
  sync master's exact decomposition), and ``all_to_all_tree`` (every
  replica's gradient shard -> the shard owner; the wrapper needs each
  replica's OWN gradient per shard because its semantics average the
  per-replica Adam UPDATES, which are nonlinear in the gradients — an
  all-to-all moves exactly the reduce-scatter's (K-1)/K byte count, so
  the wire win is identical).
- **Spec builders** for the ``shard_map`` in/out spec trees and the
  jit in/out shardings.
- ``pack_introspection`` — the ``__introspect__`` packing for the
  wrapper's ZeRO window (per-replica gradient norms survive; update and
  param norms are computed once from the sharded trees, since the
  update is shared across replicas under ZeRO).

Semantics contract (tests/test_zero.py): a ZeRO run matches the same
master's replicated mode within rtol 1e-5 per step on params — including
Adam, the stability guard's non-finite skip/poison masking, and elastic
eviction — with zero steady-state recompiles.  Known trace differences,
documented in docs/PARALLELISM.md: dropout draws per data shard instead
of per global batch in the sync master (same key, different shape), and
batch-norm batch statistics are per-shard (averaged into the replicated
net state), mirroring the wrapper's existing per-replica semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.observability.shardstats import (
    RESERVED_REPLICATED_SUBTREES, zero_shardable,
)

REPLICATED = "replicated"
ZERO = "zero"
MODES = (REPLICATED, ZERO)

AXIS = backend.AXIS_DATA


def validate_mode(update_sharding: str, mesh) -> str:
    """Validate an ``update_sharding=`` constructor argument against the
    mesh.  ZeRO requires a pure data-parallel mesh: the reduce-scatter /
    all-gather pair is laid over the 'data' axis, and a live model/seq
    axis would need a 2-D sharding composition this mode does not
    implement (compose via the pipeline/TP masters instead)."""
    if update_sharding not in MODES:
        raise ValueError(
            f"update_sharding must be one of {MODES}, "
            f"got {update_sharding!r}")
    if update_sharding == ZERO:
        sizes = dict(mesh.shape)
        extra = 1
        for ax in (backend.AXIS_MODEL, backend.AXIS_SEQ):
            extra *= int(sizes.get(ax, 1))
        if extra != 1:
            raise ValueError(
                "update_sharding='zero' needs a pure data-parallel mesh "
                f"(model*seq axes must be 1, got {extra})")
        if mesh.shape[AXIS] < 2:
            raise ValueError(
                "update_sharding='zero' needs a data axis of at least 2 "
                f"devices (got {mesh.shape[AXIS]}) — on one device there "
                "is nothing to shard")
    return update_sharding


def no_norm(cfg):
    """A copy of an ``UpdaterConfig`` with gradient normalization
    disabled — the ZeRO paths normalize per replica on the FULL gradient
    (exactly like replicated mode, where the per-layer norms span the
    whole layer) before the gradients are scattered, so the sharded
    elementwise updater must not re-normalize on shard-local norms."""
    if cfg.gradient_normalization == "none":
        return cfg
    return dataclasses.replace(cfg, gradient_normalization="none")


class ZeroLayout:
    """Per-leaf ZeRO sharding decisions over the mesh's data axis."""

    def __init__(self, mesh, k: Optional[int] = None):
        self.mesh = mesh
        self.k = int(k if k is not None else mesh.shape[AXIS])
        self._repl = NamedSharding(mesh, P())

    # ------------------------------------------------------------ per leaf
    def shardable(self, leaf) -> bool:
        return zero_shardable(getattr(leaf, "shape", ()), self.k)

    def spec(self, leaf) -> P:
        return P(AXIS) if self.shardable(leaf) else P()

    def sharding(self, leaf) -> NamedSharding:
        return (NamedSharding(self.mesh, P(AXIS)) if self.shardable(leaf)
                else self._repl)

    # ------------------------------------------------------------ per tree
    def mask(self, tree):
        """Pytree of booleans: which leaves shard.  Computed from GLOBAL
        shapes, so it can be closed over by ``shard_map`` bodies whose
        blocks carry divided shapes."""
        return jax.tree_util.tree_map(self.shardable, tree)

    def tree_specs(self, tree):
        return jax.tree_util.tree_map(self.spec, tree)

    def tree_shardings(self, tree):
        return jax.tree_util.tree_map(self.sharding, tree)

    def place(self, tree):
        """Device-put a host/replicated tree into the ZeRO layout."""
        return jax.device_put(tree, self.tree_shardings(tree))

    def upd_shardings(self, upd_state, reserved_sharding=None):
        """Shardings for an updater-state tree: inner optimizer slots
        (Adam moments & co) take the per-leaf ZeRO layout; the reserved
        ``__stability__`` / ``__introspect__`` subtrees take
        ``reserved_sharding`` (default: replicated — the sync master's
        choice; the wrapper passes its stacked-per-replica sharding)."""
        reserved = (reserved_sharding if reserved_sharding is not None
                    else self._repl)
        return {
            slot: (jax.tree_util.tree_map(lambda _l: reserved, tree)
                   if slot in RESERVED_REPLICATED_SUBTREES
                   else self.tree_shardings(tree))
            for slot, tree in upd_state.items()
        }

    def place_updater(self, upd_state, reserved_place=None):
        """Device-put an updater-state tree into the ZeRO layout;
        ``reserved_place(subtree)`` overrides placement of the reserved
        subtrees (the wrapper stacks them per replica)."""
        out = {}
        for slot, tree in upd_state.items():
            if slot in RESERVED_REPLICATED_SUBTREES:
                out[slot] = (reserved_place(tree) if reserved_place
                             else jax.device_put(tree, self._repl))
            else:
                out[slot] = self.place(tree)
        return out

    def notes(self) -> Dict[str, Any]:
        """The ledger provenance record for this layout."""
        return {"update_sharding": ZERO,
                "data_axis_size": self.k,
                "reserved_subtrees": {
                    k: "replicated" for k in RESERVED_REPLICATED_SUBTREES}}


# ---------------------------------------------------------------------------
# collective helpers — call these INSIDE a shard_map body over the data axis
# ---------------------------------------------------------------------------

def all_gather_tree(blocks, mask):
    """Sharded param blocks -> full leaves (the pre-forward gather).
    ``mask`` is ``ZeroLayout.mask`` of the GLOBAL tree; non-sharded
    leaves pass through untouched."""
    return jax.tree_util.tree_map(
        lambda m, b: lax.all_gather(b, AXIS, axis=0, tiled=True) if m else b,
        mask, blocks)


def reduce_scatter_tree(full, k: int):
    """Per-device gradient contributions -> summed shards.  Shardable
    leaves take a genuine reduce-scatter (each device receives the sum
    of its 1/K slice); non-dividing leaves fall back to a (small)
    all-reduce and stay replicated — the same split the layout applies
    to the state they update."""
    def rs(leaf):
        if zero_shardable(leaf.shape, k):
            return lax.psum_scatter(leaf, AXIS, scatter_dimension=0,
                                    tiled=True)
        return lax.psum(leaf, AXIS)

    return jax.tree_util.tree_map(rs, full)


def all_to_all_tree(full, k: int):
    """One replica's full gradient -> every replica's shard, stacked.
    Shardable leaves of shape ``[d0, ...]`` come back as ``[K, d0/K,
    ...]`` blocks (globally ``[K, d0, ...]`` sharded on dim 1): the
    leading axis indexes the REPLICA, the rest is this device's shard of
    that replica's gradient.  Non-dividing leaves all-gather to ``[K,
    d0, ...]`` replicated.  This is the wrapper's collective: its
    averaging semantics need each replica's own gradient at the shard
    owner (the per-replica Adam updates it averages are nonlinear in the
    gradients), and the all-to-all moves exactly the reduce-scatter's
    (K-1)/K bytes per device."""
    def a2a(leaf):
        if zero_shardable(leaf.shape, k):
            pieces = leaf.reshape((k, leaf.shape[0] // k) + leaf.shape[1:])
            return lax.all_to_all(pieces, AXIS, split_axis=0, concat_axis=0,
                                  tiled=False)
        return lax.all_gather(leaf, AXIS, axis=0, tiled=False)

    return jax.tree_util.tree_map(a2a, full)


def grad_stack_specs(tree, k: int):
    """``shard_map`` out_specs for an ``all_to_all_tree`` result: the
    replica axis is unsharded, the shard axis is dim 1."""
    return jax.tree_util.tree_map(
        lambda leaf: (P(None, AXIS) if zero_shardable(leaf.shape, k)
                      else P()),
        tree)


# ---------------------------------------------------------------------------
# introspection packing for the wrapper's ZeRO window
# ---------------------------------------------------------------------------

def pack_introspection(plan, iteration, grad_norms_k, update_norm,
                       param_norm, act_stats_k=None):
    """Build the stacked ``[K, N]`` ``__introspect__`` state for a ZeRO
    wrapper window: per-replica gradient norms (``[K, L]``, measured on
    each replica's own unscaled gradient before the scatter), shared
    update/param norms (``[L]``, broadcast — under ZeRO every replica
    applies the same averaged update), and per-replica activation stats
    (``[K, A]``) when the plan collects them.  Field order matches
    ``introspection.collect``."""
    K = grad_norms_k.shape[0]
    it = jnp.broadcast_to(
        jnp.asarray(iteration, jnp.float32).reshape(1, 1), (K, 1))
    un = jnp.broadcast_to(update_norm[None, :], grad_norms_k.shape)
    pn = jnp.broadcast_to(param_norm[None, :], grad_norms_k.shape)
    parts = [it, grad_norms_k, un, pn]
    if plan.act_names:
        if act_stats_k is None:
            raise ValueError(
                "plan collects activations but no act_stats were passed")
        parts += [act_stats_k["act_mean"], act_stats_k["act_std"],
                  act_stats_k["act_zero"]]
    return {"packed": jnp.concatenate(parts, axis=1)}


def tree_norms(plan, tree):
    """Per-layer L2 norms ``[L]`` of a (possibly sharded) tree in
    ``plan.grad_names`` order — under GSPMD the reductions over sharded
    leaves are global, so the values equal the replicated-mode norms."""
    from deeplearning4j_tpu.observability.introspection import _sq_sum

    return jnp.stack([
        jnp.sqrt(_sq_sum(tree.get(name, {}) if hasattr(tree, "get")
                         else tree[name]))
        for name in plan.grad_names])


def update_delta_norms(plan, old_params, new_params):
    """Per-layer L2 norms of ``old - new`` (the applied update) over
    sharded trees — global values via GSPMD."""
    from deeplearning4j_tpu.observability.introspection import _sq_sum

    return jnp.stack([
        jnp.sqrt(_sq_sum(jax.tree_util.tree_map(
            lambda o, n: o.astype(jnp.float32) - n.astype(jnp.float32),
            old_params[name], new_params[name])))
        for name in plan.grad_names])
