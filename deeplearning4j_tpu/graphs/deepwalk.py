"""DeepWalk: random-walk + skip-gram vertex embeddings.

Reference: ``graph/models/deepwalk/DeepWalk.java`` (Builder: vectorSize,
windowSize, learningRate, walkLength, walksPerVertex; fit(graph) generates
walks and trains skip-gram over them with a ``GraphHuffman`` tree +
``InMemoryGraphLookupTable``), ``models/GraphVectors.java`` query surface
(similarity, verticesNearest).

TPU redesign: walks come from the vectorised ``generate_walks`` sweep and
train through the SAME batched SequenceVectors engine as Word2Vec —
hierarchical softmax over a Huffman tree on vertex visit-frequencies (the
GraphHuffman equivalent is the shared ``vocab.build_huffman``).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graphs.api import Graph
from deeplearning4j_tpu.graphs.walks import generate_walks
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors, VectorsConfiguration
from deeplearning4j_tpu.nlp.vocab import Sequence, VocabWord


class DeepWalk:
    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, walk_length: int = 40,
                 walks_per_vertex: int = 10, epochs: int = 1,
                 negative: int = 0, use_hierarchic_softmax: bool = True,
                 batch_size: int = 512, seed: int = 12345):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.epochs = epochs
        self.negative = negative
        self.use_hierarchic_softmax = use_hierarchic_softmax
        self.batch_size = batch_size
        self.seed = seed
        self._sv: Optional[SequenceVectors] = None
        self.graph: Optional[Graph] = None

    # ---------------------------------------------------------------- fit
    def fit(self, graph: Graph) -> "DeepWalk":
        self.graph = graph
        walks = generate_walks(graph, self.walk_length, self.walks_per_vertex,
                               seed=self.seed)

        def sequences():
            for row in walks:
                seq = Sequence()
                for v in row:
                    seq.add_element(VocabWord(label=str(int(v))))
                yield seq

        cfg = VectorsConfiguration(
            layer_size=self.vector_size,
            window=self.window_size,
            learning_rate=self.learning_rate,
            negative=self.negative,
            use_hierarchic_softmax=self.use_hierarchic_softmax,
            min_word_frequency=1,
            epochs=self.epochs,
            batch_size=self.batch_size,
            seed=self.seed,
        )
        self._sv = SequenceVectors(cfg, sequences)
        self._sv.fit()
        return self

    # -------------------------------------------------- GraphVectors query
    @property
    def lookup(self):
        return self._sv.lookup

    @property
    def vocab(self):
        return self._sv.vocab

    def num_vertices(self) -> int:
        return self.graph.num_vertices if self.graph else 0

    def vertex_vector(self, idx: int) -> np.ndarray:
        return self._sv.get_word_vector(str(idx))

    def similarity(self, a: int, b: int) -> float:
        """≙ ``GraphVectorsImpl.similarity``."""
        return self._sv.similarity(str(a), str(b))

    def vertices_nearest(self, idx: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self._sv.words_nearest(str(idx), top_n=top_n)]
