"""Adjacency-list graph primitives.

Reference: ``deeplearning4j-graph/.../graph/api/{IGraph,Vertex,Edge}.java``
and ``graph/graph/Graph.java`` (adjacency-list digraph with optional
undirected semantics, NoEdgeHandling for dead-end walks).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Generic, List, Optional, Sequence, TypeVar

import numpy as np

T = TypeVar("T")


@dataclass(frozen=True)
class Vertex(Generic[T]):
    """≙ ``api/Vertex.java`` — index + arbitrary value."""

    idx: int
    value: Any = None


@dataclass(frozen=True)
class Edge:
    """≙ ``api/Edge.java``."""

    src: int
    dst: int
    weight: float = 1.0
    directed: bool = False


class NoEdges(Exception):
    """≙ ``exception/NoEdgesException.java`` — walk hit a dead end with
    NoEdgeHandling.EXCEPTION_ON_DISCONNECTED."""


class Graph:
    """≙ ``graph/graph/Graph.java``."""

    def __init__(self, num_vertices: int, allow_multiple_edges: bool = True,
                 vertices: Optional[Sequence[Vertex]] = None):
        self.num_vertices = num_vertices
        self.allow_multiple_edges = allow_multiple_edges
        self._vertices = (list(vertices) if vertices is not None
                          else [Vertex(i) for i in range(num_vertices)])
        self._adj: List[List[Edge]] = [[] for _ in range(num_vertices)]

    # ------------------------------------------------------------- mutation
    def add_edge(self, src: int, dst: int, weight: float = 1.0,
                 directed: bool = False) -> None:
        if not (0 <= src < self.num_vertices and 0 <= dst < self.num_vertices):
            raise ValueError(f"Edge ({src},{dst}) out of range 0..{self.num_vertices - 1}")
        e = Edge(src, dst, weight, directed)
        if not self.allow_multiple_edges and any(
                x.dst == dst for x in self._adj[src]):
            return
        self._adj[src].append(e)
        if not directed and src != dst:
            self._adj[dst].append(Edge(dst, src, weight, directed))

    # -------------------------------------------------------------- queries
    def vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def vertices(self) -> List[Vertex]:
        return list(self._vertices)

    def edges_out(self, idx: int) -> List[Edge]:
        return list(self._adj[idx])

    def neighbors(self, idx: int) -> List[int]:
        return [e.dst for e in self._adj[idx]]

    def degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def num_edges(self) -> int:
        return sum(len(a) for a in self._adj)

    # ------------------------------------------ dense forms (TPU-friendly)
    def neighbor_table(self, pad: int = -1):
        """Dense [V, max_degree] neighbor indices + degree vector — the
        shape random-walk kernels batch over."""
        V = self.num_vertices
        max_deg = max((len(a) for a in self._adj), default=1) or 1
        table = np.full((V, max_deg), pad, np.int32)
        weights = np.zeros((V, max_deg), np.float32)
        deg = np.zeros((V,), np.int32)
        for i, adj in enumerate(self._adj):
            deg[i] = len(adj)
            for j, e in enumerate(adj):
                table[i, j] = e.dst
                weights[i, j] = e.weight
        return table, weights, deg
