"""Graph file loaders.

Reference: ``graph/data/GraphLoader.java`` +
``impl/{DelimitedEdgeLineProcessor,WeightedEdgeLineProcessor,
DelimitedVertexLoader}.java`` — delimited "src<sep>dst[<sep>weight]" edge
lists and "idx<sep>value" vertex files, comment lines skipped.
"""

from __future__ import annotations

from typing import List, Optional

from deeplearning4j_tpu.graphs.api import Graph, Vertex


def _lines(path: str, skip_prefix: str):
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or (skip_prefix and line.startswith(skip_prefix)):
                continue
            yield line


def load_delimited_edges(path: str, num_vertices: int, delimiter: str = ",",
                         directed: bool = False,
                         skip_prefix: str = "#") -> Graph:
    """≙ ``GraphLoader.loadUndirectedGraphEdgeListFile`` /
    DelimitedEdgeLineProcessor."""
    g = Graph(num_vertices)
    for line in _lines(path, skip_prefix):
        parts = line.split(delimiter)
        g.add_edge(int(parts[0]), int(parts[1]), directed=directed)
    return g


def load_weighted_edges(path: str, num_vertices: int, delimiter: str = ",",
                        directed: bool = False,
                        skip_prefix: str = "#") -> Graph:
    """≙ ``WeightedEdgeLineProcessor``: src,dst,weight."""
    g = Graph(num_vertices)
    for line in _lines(path, skip_prefix):
        parts = line.split(delimiter)
        g.add_edge(int(parts[0]), int(parts[1]), weight=float(parts[2]),
                   directed=directed)
    return g


def load_delimited_vertices(path: str, delimiter: str = ",",
                            skip_prefix: str = "#") -> List[Vertex]:
    """≙ ``DelimitedVertexLoader``: "idx<sep>value" per line."""
    out = []
    for line in _lines(path, skip_prefix):
        idx, _, value = line.partition(delimiter)
        out.append(Vertex(int(idx), value))
    return out
