"""Random walks over graphs.

Reference: ``graph/iterator/RandomWalkIterator.java`` (uniform next-hop,
walkLength steps, NoEdgeHandling SELF_LOOP_ON_DISCONNECTED default),
``WeightedRandomWalkIterator.java`` (edge-weight-proportional hops), and the
parallel iterator providers.

TPU redesign: besides the iterator surface, ``generate_walks`` produces ALL
walks in one vectorised sweep — a [V, L] matrix built with numpy row-gathers
over the dense neighbor table (the batched analogue of the reference's
thread-parallel iterator providers; feeds straight into the batched
SequenceVectors kernels).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graphs.api import Graph, NoEdges


class RandomWalkIterator:
    """Uniform random walks, one per starting vertex (in order).
    ≙ ``RandomWalkIterator.java``."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 12345,
                 no_edge_handling: str = "self_loop"):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self.reset()

    def reset(self) -> None:
        self._rs = np.random.RandomState(self.seed)
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < self.graph.num_vertices

    def next(self) -> List[int]:
        start = self._pos
        self._pos += 1
        return self._walk_from(start)

    def _next_hop(self, cur: int) -> int:
        nbrs = self.graph.neighbors(cur)
        if not nbrs:
            if self.no_edge_handling == "self_loop":
                return cur
            raise NoEdges(f"Vertex {cur} has no outgoing edges")
        return nbrs[self._rs.randint(len(nbrs))]

    def _walk_from(self, start: int) -> List[int]:
        walk = [start]
        cur = start
        for _ in range(self.walk_length):
            cur = self._next_hop(cur)
            walk.append(cur)
        return walk

    def __iter__(self) -> Iterator[List[int]]:
        self.reset()
        while self.has_next():
            yield self.next()


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Next hop ∝ edge weight. ≙ ``WeightedRandomWalkIterator.java``."""

    def _next_hop(self, cur: int) -> int:
        edges = self.graph.edges_out(cur)
        if not edges:
            if self.no_edge_handling == "self_loop":
                return cur
            raise NoEdges(f"Vertex {cur} has no outgoing edges")
        w = np.array([e.weight for e in edges], np.float64)
        p = w / w.sum()
        return edges[self._rs.choice(len(edges), p=p)].dst


def generate_walks(graph: Graph, walk_length: int, walks_per_vertex: int = 1,
                   seed: int = 12345, weighted: bool = False) -> np.ndarray:
    """All walks at once: [V * walks_per_vertex, walk_length+1] int32.

    Vectorised over every active walk per step (gather next-hop candidates
    from the dense neighbor table, sample once per row) — the batched
    replacement for the reference's per-thread iterator providers
    (``iterator/parallel/RandomWalkGraphIteratorProvider.java``).
    """
    table, weights, deg = graph.neighbor_table()
    V = graph.num_vertices
    rs = np.random.RandomState(seed)
    starts = np.tile(np.arange(V, dtype=np.int32), walks_per_vertex)
    n = len(starts)
    walks = np.empty((n, walk_length + 1), np.int32)
    walks[:, 0] = starts
    cur = starts.copy()
    for t in range(1, walk_length + 1):
        d = deg[cur]                              # [n]
        if weighted:
            w = weights[cur]                      # [n, max_deg]
            valid = np.arange(w.shape[1])[None, :] < d[:, None]
            w = np.where(valid, w, 0.0)
            tot = w.sum(1, keepdims=True)
            safe_tot = np.maximum(tot, 1e-12)
            cdf = np.cumsum(w / safe_tot, axis=1)
            u = rs.rand(n, 1)
            choice = (u > cdf).sum(1)
            choice = np.minimum(choice, np.maximum(d - 1, 0))
        else:
            choice = (rs.rand(n) * np.maximum(d, 1)).astype(np.int64)
        nxt = table[cur, choice]
        # dead ends: self-loop (reference SELF_LOOP_ON_DISCONNECTED)
        cur = np.where(d > 0, nxt, cur).astype(np.int32)
        walks[:, t] = cur
    return walks
