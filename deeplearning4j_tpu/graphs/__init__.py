"""Graph embeddings module (≙ deeplearning4j-graph).

Graph API + loaders + random walks + DeepWalk; embedding training rides the
shared SequenceVectors engine (walks are just sequences of vertex labels),
replacing the reference's bespoke ``InMemoryGraphLookupTable``/``BinaryTree``
Hogwild path with the same batched TPU kernels as Word2Vec.
"""

from deeplearning4j_tpu.graphs.api import Edge, Graph, Vertex
from deeplearning4j_tpu.graphs.loaders import (
    load_delimited_edges,
    load_delimited_vertices,
    load_weighted_edges,
)
from deeplearning4j_tpu.graphs.walks import (
    RandomWalkIterator,
    WeightedRandomWalkIterator,
    generate_walks,
)
from deeplearning4j_tpu.graphs.deepwalk import DeepWalk

__all__ = [
    "Edge", "Graph", "Vertex", "load_delimited_edges",
    "load_delimited_vertices", "load_weighted_edges", "RandomWalkIterator",
    "WeightedRandomWalkIterator", "generate_walks", "DeepWalk",
]
