"""Native runtime core — numpy-facing wrappers over the C++ library.

Every entry point has a pure-Python fallback producing identical results, so
behavior is independent of whether the .so built; the native path is the
fast one (multithreaded parse/gather, C++ prefetch pipeline).  Reference
roles covered: DataVec record parsing, MnistManager IDX decoding
(``deeplearning4j-core/.../datasets/mnist/MnistManager.java``), the
AsyncDataSetIterator producer thread
(``deeplearning4j-nn/.../iterator/AsyncDataSetIterator.java:36-76``), and the
batch-and-export DataSet files (``spark/data/BatchAndExportDataSetsFunction.java``).
"""

from __future__ import annotations

import ctypes
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.native.loader import available, lib

__all__ = [
    "available", "csv_to_matrix", "parse_idx_images", "parse_idx_labels",
    "gather_rows", "Batcher", "write_dataset", "read_dataset",
    "dataset_header",
]

_MAGIC = 0x44344A54


def _fp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def csv_to_matrix(data: bytes, delimiter: str = ",", skip_lines: int = 0,
                  force_python: bool = False) -> np.ndarray:
    """Parse an all-numeric CSV byte buffer into a float32 matrix."""
    L = None if force_python else lib()
    if L is not None:
        n_cols = ctypes.c_long(0)
        rows = L.csv_dims(data, len(data), delimiter.encode(), skip_lines,
                          ctypes.byref(n_cols))
        if rows > 0 and n_cols.value > 0:
            out = np.empty((rows, n_cols.value), np.float32)
            got = L.csv_parse(data, len(data), delimiter.encode(), skip_lines,
                              _fp(out), rows, n_cols.value, 0)
            if got == rows:
                return out
            # fall through to Python on parse failure (non-numeric field)
    lines = [ln for ln in data.decode("utf-8").splitlines()[skip_lines:]
             if ln.strip()]
    return np.asarray([[float(f) for f in ln.split(delimiter)] for ln in lines],
                      np.float32)


def parse_idx_images(data: bytes, force_python: bool = False) -> np.ndarray:
    """IDX3 ubyte images -> float32 [n, rows*cols] normalized to [0,1]."""
    magic, n, rows, cols = struct.unpack(">IIII", data[:16])
    if magic != 0x803:
        raise ValueError(f"bad IDX3 magic {magic:#x}")
    L = None if force_python else lib()
    if L is not None:
        out = np.empty((n, rows * cols), np.float32)
        got = L.idx_images(data, len(data), _fp(out), n, 0)
        if got == n:
            return out
    raw = np.frombuffer(data, np.uint8, count=n * rows * cols, offset=16)
    return (raw.astype(np.float32) / 255.0).reshape(n, rows * cols)


def parse_idx_labels(data: bytes, n_classes: int = 10,
                     force_python: bool = False) -> np.ndarray:
    """IDX1 ubyte labels -> one-hot float32 [n, n_classes]."""
    magic, n = struct.unpack(">II", data[:8])
    if magic != 0x801:
        raise ValueError(f"bad IDX1 magic {magic:#x}")
    L = None if force_python else lib()
    if L is not None:
        out = np.empty((n, n_classes), np.float32)
        got = L.idx_labels(data, len(data), _fp(out), n_classes, n)
        if got == n:
            return out
    raw = np.frombuffer(data, np.uint8, count=n, offset=8)
    out = np.zeros((n, n_classes), np.float32)
    valid = raw < n_classes  # out-of-range labels -> all-zero row (native parity)
    out[np.nonzero(valid)[0], raw[valid]] = 1.0
    return out


def gather_rows(src: np.ndarray, idx: np.ndarray,
                force_python: bool = False) -> np.ndarray:
    """Gather rows of a 2-D float32 array (multithreaded in native)."""
    src = np.ascontiguousarray(src, np.float32)
    idx64 = np.ascontiguousarray(idx, np.int64)
    if len(idx64) and (idx64.min() < 0 or idx64.max() >= len(src)):
        raise IndexError("gather index out of range")
    L = None if force_python else lib()
    if L is None:
        return src[idx64]
    out = np.empty((len(idx64), src.shape[1]), np.float32)
    L.gather_rows_f32(_fp(src), src.shape[1],
                      idx64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                      len(idx64), _fp(out), 0)
    return out


class Batcher:
    """Async shuffled minibatch pipeline over in-memory arrays.

    Native path: C++ producer thread + reusable buffer pool + bounded queue.
    Fallback: synchronous numpy gather with the same deterministic xorshift
    shuffle, so batch order matches bit-for-bit across both paths.
    """

    def __init__(self, features: np.ndarray, labels: Optional[np.ndarray],
                 batch_size: int, shuffle: bool = True, seed: int = 1,
                 queue_cap: int = 2, drop_last: bool = False,
                 force_python: bool = False):
        self._f = np.ascontiguousarray(
            features.reshape(len(features), -1), np.float32)
        self._l = (None if labels is None else
                   np.ascontiguousarray(labels.reshape(len(labels), -1),
                                        np.float32))
        self._fshape = features.shape[1:]
        self._lshape = None if labels is None else labels.shape[1:]
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._n = len(features)
        self._handle = None
        self._L = None if force_python else lib()
        if self._L is not None:
            # gather single-threaded: the producer thread is already off the
            # consumer's critical path, and per-batch thread spawn would cost
            # more than the copy for typical minibatch sizes
            self._handle = self._L.batcher_create(
                _fp(self._f), None if self._l is None else _fp(self._l),
                self._n, self._f.shape[1],
                0 if self._l is None else self._l.shape[1],
                batch_size, int(shuffle), seed, 1, queue_cap, int(drop_last))
        else:
            self._py_reset(seed)

    # deterministic xorshift64* Fisher-Yates matching the C++ implementation
    def _py_perm(self, seed: int) -> np.ndarray:
        perm = np.arange(self._n, dtype=np.int64)
        if not self.shuffle:
            return perm
        x = seed if seed else 0x9E3779B97F4A7C15
        mask = (1 << 64) - 1
        for i in range(self._n - 1, 0, -1):
            x ^= x >> 12; x = (x ^ (x << 25)) & mask; x ^= x >> 27
            r = (x * 0x2545F4914F6CDD1D) & mask
            j = r % (i + 1)
            perm[i], perm[j] = perm[j], perm[i]
        return perm

    def _py_reset(self, seed: int):
        self._perm = self._py_perm(seed)
        self._pos = 0

    def next(self) -> Optional[Tuple[np.ndarray, Optional[np.ndarray], int]]:
        """(features, labels, n_valid) for the next batch, or None at epoch
        end.  Short final batches are zero-padded to batch_size."""
        bs = self.batch_size
        if self._handle is not None:
            feat = np.empty((bs, self._f.shape[1]), np.float32)
            lab = (None if self._l is None else
                   np.empty((bs, self._l.shape[1]), np.float32))
            n_valid = ctypes.c_long(0)
            ok = self._L.batcher_next(
                self._handle, _fp(feat), None if lab is None else _fp(lab),
                ctypes.byref(n_valid))
            if not ok:
                return None
            nv = n_valid.value
        else:
            if self._pos >= self._n:
                return None
            idx = self._perm[self._pos:self._pos + bs]
            nv = len(idx)
            if nv < bs and self.drop_last:
                self._pos = self._n
                return None
            self._pos += bs
            feat = np.zeros((bs, self._f.shape[1]), np.float32)
            feat[:nv] = self._f[idx]
            lab = None
            if self._l is not None:
                lab = np.zeros((bs, self._l.shape[1]), np.float32)
                lab[:nv] = self._l[idx]
        feat = feat.reshape((bs,) + self._fshape)
        if lab is not None:
            lab = lab.reshape((bs,) + self._lshape)
        return feat, lab, nv

    def reset(self, seed: int = 1):
        if self._handle is not None:
            self._L.batcher_reset(self._handle, seed)
        else:
            self._py_reset(seed)

    def close(self):
        if self._handle is not None:
            self._L.batcher_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def write_dataset(path, features: np.ndarray,
                  labels: Optional[np.ndarray] = None) -> None:
    """Write the binary DataSet container (header + f32 payloads)."""
    f = np.ascontiguousarray(features.reshape(len(features), -1), np.float32)
    l = (np.zeros((len(f), 0), np.float32) if labels is None else
         np.ascontiguousarray(labels.reshape(len(labels), -1), np.float32))
    L = lib()
    if L is not None:
        rc = L.dataset_write(str(path).encode(), _fp(f), _fp(l), len(f),
                             f.shape[1], l.shape[1])
        if rc == 0:
            return
    with open(path, "wb") as fh:
        fh.write(struct.pack("<IIqqq", _MAGIC, 1, len(f), f.shape[1],
                             l.shape[1]))
        fh.write(f.tobytes())
        fh.write(l.tobytes())


def dataset_header(path) -> Tuple[int, int, int]:
    """(n, feat_elems, lab_elems) from a DataSet container's 32-byte header."""
    with open(path, "rb") as fh:
        header = fh.read(32)
    magic, _ver, n, fe, le = struct.unpack("<IIqqq", header[:32])
    if magic != _MAGIC:
        raise ValueError(f"bad dataset magic {magic:#x} in {path}")
    return n, fe, le


def read_dataset(path) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Read the binary DataSet container back."""
    n, fe, le = dataset_header(path)
    L = lib()
    feat = np.empty((n, fe), np.float32)
    labs = np.empty((n, le), np.float32)
    if L is not None and L.dataset_read(str(path).encode(), _fp(feat),
                                        _fp(labs)) == 0:
        return feat, (labs if le else None)
    with open(path, "rb") as fh:
        fh.seek(32)
        feat = np.frombuffer(fh.read(4 * n * fe), np.float32).reshape(n, fe)
        labs = (np.frombuffer(fh.read(4 * n * le), np.float32).reshape(n, le)
                if le else None)
    return feat.copy(), (None if labs is None else labs.copy())
