"""Builds/loads the native C++ runtime core (``dl4j_tpu_native.cpp``).

The reference's host-side heavy lifting is native (libnd4j host ops, DataVec
readers); here the equivalent C++ library is compiled once with the system
toolchain and loaded via ctypes.  Everything degrades gracefully: if the
toolchain is unavailable the pure-Python fallbacks in the calling modules
take over, so the framework never hard-depends on the .so.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "src" / "dl4j_tpu_native.cpp"
_SO = _HERE / "_dl4j_tpu_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

ABI_VERSION = 1


def _build() -> bool:
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(_SRC), "-o", str(_SO),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0 and _SO.exists()


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_long = ctypes.c_long
    c_fp = ctypes.POINTER(ctypes.c_float)
    c_i64p = ctypes.POINTER(ctypes.c_int64)
    c_u8p = ctypes.POINTER(ctypes.c_uint8)

    lib.dl4j_native_abi_version.restype = ctypes.c_int

    lib.csv_dims.argtypes = [ctypes.c_char_p, c_long, ctypes.c_char, c_long,
                             ctypes.POINTER(c_long)]
    lib.csv_dims.restype = c_long
    lib.csv_parse.argtypes = [ctypes.c_char_p, c_long, ctypes.c_char, c_long,
                              c_fp, c_long, c_long, ctypes.c_int]
    lib.csv_parse.restype = c_long

    lib.idx_images.argtypes = [ctypes.c_char_p, c_long, c_fp, c_long,
                               ctypes.c_int]
    lib.idx_images.restype = c_long
    lib.idx_labels.argtypes = [ctypes.c_char_p, c_long, c_fp, c_long, c_long]
    lib.idx_labels.restype = c_long

    lib.gather_rows_f32.argtypes = [c_fp, c_long, c_i64p, c_long, c_fp,
                                    ctypes.c_int]
    lib.gather_rows_f32.restype = None

    lib.batcher_create.argtypes = [c_fp, c_fp, c_long, c_long, c_long, c_long,
                                   ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
                                   ctypes.c_int, ctypes.c_int]
    lib.batcher_create.restype = ctypes.c_void_p
    lib.batcher_next.argtypes = [ctypes.c_void_p, c_fp, c_fp,
                                 ctypes.POINTER(c_long)]
    lib.batcher_next.restype = ctypes.c_int
    lib.batcher_reset.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.batcher_reset.restype = None
    lib.batcher_destroy.argtypes = [ctypes.c_void_p]
    lib.batcher_destroy.restype = None

    lib.dataset_write.argtypes = [ctypes.c_char_p, c_fp, c_fp, c_long, c_long,
                                  c_long]
    lib.dataset_write.restype = c_long
    lib.dataset_read_header.argtypes = [ctypes.c_char_p, c_i64p, c_i64p, c_i64p]
    lib.dataset_read_header.restype = c_long
    lib.dataset_read.argtypes = [ctypes.c_char_p, c_fp, c_fp]
    lib.dataset_read.restype = c_long
    return lib


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if the
    toolchain is unavailable or the build fails."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DL4J_TPU_DISABLE_NATIVE"):
            return None
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            candidate = _bind(ctypes.CDLL(str(_SO)))
            stale = candidate.dl4j_native_abi_version() != ABI_VERSION
        except (OSError, AttributeError):
            stale = True  # unloadable or missing symbols: rebuild once
        if stale:
            _SO.unlink(missing_ok=True)
            if not _build():
                return None
            try:
                candidate = _bind(ctypes.CDLL(str(_SO)))
            except (OSError, AttributeError):
                return None
        _lib = candidate
        return _lib


def available() -> bool:
    return lib() is not None
