// dl4j_tpu_native — native (C++) runtime core for the TPU framework.
//
// Role: the host-side ETL / IO / memory-management layer that the reference
// delegates to native code (libnd4j host ops + DataVec record readers +
// the AsyncDataSetIterator prefetch machinery,
// reference: deeplearning4j-nn/.../iterator/AsyncDataSetIterator.java:36-76,
// deeplearning4j-core/.../datasets/datavec/RecordReaderDataSetIterator.java,
// deeplearning4j-core/.../datasets/mnist/MnistManager.java).
//
// The TPU compute path is JAX/XLA; everything here runs on the host CPU and
// feeds it: CSV/IDX record parsing, multithreaded minibatch gather, an async
// double-buffered batch pipeline with a reusable buffer pool (the allocator),
// and a binary DataSet container format (the batch-and-export analog of
// spark/data/BatchAndExportDataSetsFunction.java).
//
// Exposed as a plain C ABI consumed from Python via ctypes (no pybind11).

#include <algorithm>
#include <atomic>
#include <cctype>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

unsigned hw_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 4 : n;
}

// Split [0, n) into roughly equal [begin, end) ranges, one per worker.
void parallel_for(long n, int n_threads, const std::function<void(long, long)>& fn) {
  if (n <= 0) return;
  int workers = n_threads > 0 ? n_threads : (int)hw_threads();
  if (workers > n) workers = (int)n;
  if (workers <= 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(workers);
  long chunk = (n + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    long b = w * chunk, e = std::min(n, b + chunk);
    if (b >= e) break;
    pool.emplace_back([&fn, b, e] { fn(b, e); });
  }
  for (auto& t : pool) t.join();
}

}  // namespace

extern "C" {

int dl4j_native_abi_version() { return 1; }

// ---------------------------------------------------------------------------
// CSV parsing (DataVec CSVRecordReader analog, numeric fast path)
// ---------------------------------------------------------------------------

// Record line start offsets after skipping `skip_lines`; returns row count.
// Blank lines are ignored.
static long csv_line_offsets(const char* buf, long len, long skip_lines,
                             std::vector<long>& offsets) {
  long pos = 0;
  for (long s = 0; s < skip_lines && pos < len; ++s) {
    const char* nl = (const char*)memchr(buf + pos, '\n', len - pos);
    if (!nl) return 0;
    pos = (nl - buf) + 1;
  }
  while (pos < len) {
    // skip blank lines
    long line_end = len;
    const char* nl = (const char*)memchr(buf + pos, '\n', len - pos);
    if (nl) line_end = nl - buf;
    bool blank = true;
    for (long i = pos; i < line_end; ++i) {
      if (!isspace((unsigned char)buf[i])) { blank = false; break; }
    }
    if (!blank) offsets.push_back(pos);
    pos = line_end + 1;
  }
  return (long)offsets.size();
}

long csv_dims(const char* buf, long len, char delim, long skip_lines,
              long* n_cols) {
  std::vector<long> offsets;
  long rows = csv_line_offsets(buf, len, skip_lines, offsets);
  if (rows == 0) { *n_cols = 0; return 0; }
  long p = offsets[0];
  long cols = 1;
  while (p < len && buf[p] != '\n') {
    if (buf[p] == delim) ++cols;
    ++p;
  }
  *n_cols = cols;
  return rows;
}

// Parse numeric CSV into row-major float32. Returns rows parsed or -1 if a
// field fails to parse (the Python layer falls back to its own reader then).
long csv_parse(const char* buf, long len, char delim, long skip_lines,
               float* out, long max_rows, long n_cols, int n_threads) {
  std::vector<long> offsets;
  long rows = csv_line_offsets(buf, len, skip_lines, offsets);
  if (rows > max_rows) rows = max_rows;
  std::atomic<bool> ok{true};
  parallel_for(rows, n_threads, [&](long b, long e) {
    for (long r = b; r < e && ok.load(std::memory_order_relaxed); ++r) {
      const char* p = buf + offsets[r];
      const char* end = buf + len;
      for (long c = 0; c < n_cols; ++c) {
        char* after = nullptr;
        double v = strtod(p, &after);
        if (after == p) { ok.store(false); return; }
        out[r * n_cols + c] = (float)v;
        p = after;
        // only whitespace may follow the number inside a field ('1.5abc'
        // must fail, matching the Python fallback's float() ValueError)
        while (p < end && *p != delim && *p != '\n') {
          if (*p != ' ' && *p != '\t' && *p != '\r') { ok.store(false); return; }
          ++p;
        }
        if (c + 1 < n_cols) {
          if (p >= end || *p != delim) { ok.store(false); return; }
          ++p;
        } else if (p < end && *p == delim) {
          // ragged row with MORE fields than the first row: refuse rather
          // than silently dropping data (parity with the Python fallback)
          ok.store(false);
          return;
        }
      }
    }
  });
  return ok.load() ? rows : -1;
}

// ---------------------------------------------------------------------------
// IDX (MNIST ubyte) parsing — MnistManager/MnistImageFile analog
// ---------------------------------------------------------------------------

static uint32_t be32(const uint8_t* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

// images: magic 0x803, n, rows, cols, then n*rows*cols ubyte. Output
// float32 normalized to [0,1]. Returns item count or -1 on bad magic.
long idx_images(const uint8_t* buf, long len, float* out, long max_items,
                int n_threads) {
  if (len < 16 || be32(buf) != 0x00000803) return -1;
  long n = be32(buf + 4), rows = be32(buf + 8), cols = be32(buf + 12);
  if (n > max_items) n = max_items;
  long item = rows * cols;
  if (16 + n * item > len) return -1;
  const uint8_t* data = buf + 16;
  parallel_for(n * item, n_threads, [&](long b, long e) {
    for (long i = b; i < e; ++i) out[i] = (float)data[i] * (1.0f / 255.0f);
  });
  return n;
}

// labels: magic 0x801, n, then n ubyte. One-hot float32 output.
long idx_labels(const uint8_t* buf, long len, float* out_onehot,
                long n_classes, long max_items) {
  if (len < 8 || be32(buf) != 0x00000801) return -1;
  long n = be32(buf + 4);
  if (n > max_items) n = max_items;
  if (8 + n > len) return -1;
  memset(out_onehot, 0, sizeof(float) * (size_t)(n * n_classes));
  for (long i = 0; i < n; ++i) {
    long c = buf[8 + i];
    if (c < n_classes) out_onehot[i * n_classes + c] = 1.0f;
  }
  return n;
}

// ---------------------------------------------------------------------------
// Multithreaded minibatch gather (the batch-assembly hot loop)
// ---------------------------------------------------------------------------

void gather_rows_f32(const float* src, long row_elems, const int64_t* idx,
                     long n_idx, float* dst, int n_threads) {
  parallel_for(n_idx, n_threads, [&](long b, long e) {
    for (long i = b; i < e; ++i) {
      memcpy(dst + i * row_elems, src + idx[i] * row_elems,
             sizeof(float) * (size_t)row_elems);
    }
  });
}

// ---------------------------------------------------------------------------
// Async batch pipeline — AsyncDataSetIterator.java:36-76 redesigned in C++:
// a producer thread assembles shuffled minibatches into buffers drawn from a
// fixed pool (the memory-management piece: buffers are reused, never
// reallocated) and hands them over a bounded queue; the consumer (Python)
// copies out and recycles the buffer.
// ---------------------------------------------------------------------------

namespace {

struct Batch {
  float* feat;
  float* lab;
  long n_valid;
};

struct Batcher {
  const float* features;
  const float* labels;
  long n, feat_elems, lab_elems, batch_size;
  bool shuffle, drop_last;
  int gather_threads;

  std::vector<int64_t> perm;
  std::vector<std::vector<float>> feat_pool, lab_pool;

  std::queue<Batch> ready;
  std::queue<int> free_bufs;
  std::vector<Batch> slots;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  bool done = false, stop = false;
  std::thread producer;
  uint64_t seed;

  void make_perm(uint64_t s) {
    perm.resize(n);
    for (long i = 0; i < n; ++i) perm[i] = i;
    if (shuffle) {
      // xorshift64* Fisher-Yates — deterministic given the seed
      uint64_t x = s ? s : 0x9E3779B97F4A7C15ull;
      for (long i = n - 1; i > 0; --i) {
        x ^= x >> 12; x ^= x << 25; x ^= x >> 27;
        uint64_t r = x * 0x2545F4914F6CDD1Dull;
        long j = (long)(r % (uint64_t)(i + 1));
        std::swap(perm[i], perm[j]);
      }
    }
  }

  void run() {
    long n_batches = drop_last ? n / batch_size
                               : (n + batch_size - 1) / batch_size;
    for (long b = 0; b < n_batches; ++b) {
      int slot;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] { return stop || !free_bufs.empty(); });
        if (stop) return;
        slot = free_bufs.front();
        free_bufs.pop();
      }
      long begin = b * batch_size;
      long count = std::min(batch_size, n - begin);
      gather_rows_f32(features, feat_elems, perm.data() + begin, count,
                      slots[slot].feat, gather_threads);
      if (labels) {
        gather_rows_f32(labels, lab_elems, perm.data() + begin, count,
                        slots[slot].lab, gather_threads);
      }
      if (count < batch_size) {
        memset(slots[slot].feat + count * feat_elems, 0,
               sizeof(float) * (size_t)((batch_size - count) * feat_elems));
        if (labels)
          memset(slots[slot].lab + count * lab_elems, 0,
                 sizeof(float) * (size_t)((batch_size - count) * lab_elems));
      }
      slots[slot].n_valid = count;
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.push(slots[slot]);
      }
      cv_ready.notify_one();
    }
    {
      std::lock_guard<std::mutex> lk(mu);
      done = true;
    }
    cv_ready.notify_all();
  }
};

}  // namespace

void* batcher_create(const float* features, const float* labels, long n,
                     long feat_elems, long lab_elems, long batch_size,
                     int shuffle, uint64_t seed, int gather_threads,
                     int queue_cap, int drop_last) {
  auto* b = new Batcher();
  b->features = features;
  b->labels = labels;
  b->n = n;
  b->feat_elems = feat_elems;
  b->lab_elems = lab_elems;
  b->batch_size = batch_size;
  b->shuffle = shuffle != 0;
  b->drop_last = drop_last != 0;
  b->gather_threads = gather_threads;
  b->seed = seed;
  b->make_perm(seed);
  int n_slots = queue_cap + 1;
  b->feat_pool.resize(n_slots);
  b->lab_pool.resize(n_slots);
  b->slots.resize(n_slots);
  for (int i = 0; i < n_slots; ++i) {
    b->feat_pool[i].resize((size_t)batch_size * feat_elems);
    b->lab_pool[i].resize(labels ? (size_t)batch_size * lab_elems : 0);
    b->slots[i] = {b->feat_pool[i].data(),
                   labels ? b->lab_pool[i].data() : nullptr, 0};
    b->free_bufs.push(i);
  }
  b->producer = std::thread([b] { b->run(); });
  return b;
}

int batcher_next(void* handle, float* feat_out, float* lab_out,
                 long* n_valid) {
  auto* b = (Batcher*)handle;
  Batch batch;
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->cv_ready.wait(lk, [&] { return b->done || !b->ready.empty(); });
    if (b->ready.empty()) return 0;
    batch = b->ready.front();
    b->ready.pop();
  }
  memcpy(feat_out, batch.feat,
         sizeof(float) * (size_t)(b->batch_size * b->feat_elems));
  if (b->labels && lab_out)
    memcpy(lab_out, batch.lab,
           sizeof(float) * (size_t)(b->batch_size * b->lab_elems));
  *n_valid = batch.n_valid;
  // recycle the buffer
  for (size_t i = 0; i < b->slots.size(); ++i) {
    if (b->slots[i].feat == batch.feat) {
      std::lock_guard<std::mutex> lk(b->mu);
      b->free_bufs.push((int)i);
      break;
    }
  }
  b->cv_free.notify_one();
  return 1;
}

static void batcher_join(Batcher* b) {
  {
    std::lock_guard<std::mutex> lk(b->mu);
    b->stop = true;
  }
  b->cv_free.notify_all();
  if (b->producer.joinable()) b->producer.join();
  b->stop = false;
}

void batcher_reset(void* handle, uint64_t seed) {
  auto* b = (Batcher*)handle;
  batcher_join(b);
  std::queue<Batch>().swap(b->ready);
  std::queue<int>().swap(b->free_bufs);
  for (size_t i = 0; i < b->slots.size(); ++i) b->free_bufs.push((int)i);
  b->done = false;
  b->make_perm(seed);
  b->producer = std::thread([b] { b->run(); });
}

void batcher_destroy(void* handle) {
  auto* b = (Batcher*)handle;
  batcher_join(b);
  delete b;
}

// ---------------------------------------------------------------------------
// Binary DataSet container — batch-and-export / portable-iterator analog
// (spark/data/BatchAndExportDataSetsFunction.java + spark/iterator/*).
// Layout: magic 'D4JT' | u32 version | i64 n | i64 feat_elems | i64 lab_elems
//         | features f32[n*feat_elems] | labels f32[n*lab_elems]
// ---------------------------------------------------------------------------

static const uint32_t kMagic = 0x44344A54;  // 'D4JT'

long dataset_write(const char* path, const float* features,
                   const float* labels, long n, long feat_elems,
                   long lab_elems) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  uint32_t header[2] = {kMagic, 1};
  int64_t dims[3] = {n, feat_elems, lab_elems};
  long ok = fwrite(header, sizeof(header), 1, f) == 1 &&
            fwrite(dims, sizeof(dims), 1, f) == 1 &&
            fwrite(features, sizeof(float), (size_t)(n * feat_elems), f) ==
                (size_t)(n * feat_elems) &&
            (lab_elems == 0 ||
             fwrite(labels, sizeof(float), (size_t)(n * lab_elems), f) ==
                 (size_t)(n * lab_elems));
  fclose(f);
  return ok ? 0 : -1;
}

long dataset_read_header(const char* path, int64_t* n, int64_t* feat_elems,
                         int64_t* lab_elems) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint32_t header[2];
  int64_t dims[3];
  long ok = fread(header, sizeof(header), 1, f) == 1 &&
            fread(dims, sizeof(dims), 1, f) == 1 && header[0] == kMagic;
  fclose(f);
  if (!ok) return -1;
  *n = dims[0];
  *feat_elems = dims[1];
  *lab_elems = dims[2];
  return 0;
}

long dataset_read(const char* path, float* features, float* labels) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint32_t header[2];
  int64_t dims[3];
  long ok = fread(header, sizeof(header), 1, f) == 1 &&
            fread(dims, sizeof(dims), 1, f) == 1 && header[0] == kMagic;
  if (ok) {
    size_t fe = (size_t)(dims[0] * dims[1]), le = (size_t)(dims[0] * dims[2]);
    ok = fread(features, sizeof(float), fe, f) == fe &&
         (le == 0 || fread(labels, sizeof(float), le, f) == le);
  }
  fclose(f);
  return ok ? 0 : -1;
}

}  // extern "C"
