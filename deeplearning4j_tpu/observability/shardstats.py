"""Memory & collective-communication observability: the sharding ledger.

PR 7 gave every compiled signature a FLOPs number (``jit_cost_analysis``)
and PR 1 gave the process PJRT device-memory gauges — but nothing reports
the third axis: WHERE the bytes live and WHAT the collectives move.
ROADMAP item 2 (ZeRO-style sharding of the weight update, arXiv
2004.13336) cannot land against guesses; this module provides the
measured baselines it will regress against, in the memory-accounting
spirit of "Memory-efficient array redistribution" (arXiv 2112.01075):

- **Per-program HLO accounting** (``program_analysis``): the compiled
  step's ``memory_analysis()`` (argument/output/temp/alias bytes →
  ``dl4j_program_memory_bytes{fn,kind}``) plus a **collective census**
  of the compiled HLO text — count and payload bytes of every
  ``all-reduce`` / ``all-gather`` / ``reduce-scatter`` /
  ``collective-permute`` / ``all-to-all`` instruction, with the replica
  group size recovered where the HLO records it
  (``dl4j_step_collective_bytes{fn,op}`` /
  ``dl4j_step_collectives_total{fn,op}``).  Harvested once per abstract
  signature through the ``RecompileDetector.check(cost_fn=)`` seam —
  exactly like ``jit_cost_analysis``, on ``ShapeDtypeStruct``s, so
  donated buffers are never touched and nothing executes.
- **The sharding ledger** (``sharding_ledger`` / ``record_ledger``):
  walk params/updater/net-state pytrees with their ACTUAL shardings and
  report per-device bytes, replication factor per tree and subtree, and
  a projected-ZeRO column (bytes per device if the tree were
  reduce-scattered over the data axis) →
  ``dl4j_sharded_bytes{component,tree}`` /
  ``dl4j_replication_factor{component,tree}`` plus the human-readable
  ``format_ledger`` report.  The walk reads only shape/dtype/sharding
  metadata — never a buffer, never a device sync.
- **A comm roofline**: a per-backend link-bandwidth table
  (``LINK_BANDWIDTH`` — single owner, like ``profiling.PEAK_FLOPS``)
  turns censused collective bytes into estimated comm seconds per step
  and a comm/compute ratio
  (``dl4j_step_comm_seconds{fn}`` /
  ``dl4j_step_comm_compute_ratio{fn}``).

Census caveats (docs/observability.md "Memory & communication"): the
census counts instructions in the compiled module ONCE — a collective
inside a ``while``/``scan`` body executes once per trip but is counted
once; XLA may fuse several logical all-reduces into one variadic
instruction (the BYTES stay right, the COUNT drops); and bytes are
payload bytes (max of operand/result size), not wire bytes — the
roofline applies the ring factor, the census does not.

Hot-loop cost while a collector is installed: one dict-identity check
plus a few cached counter increments per dispatch; the lower+compile
for the census happens once per NEW signature (steady state: never).
"""

from __future__ import annotations

import logging
import math
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

_PROGRAM_MEMORY = "dl4j_program_memory_bytes"
_COLL_BYTES = "dl4j_step_collective_bytes"
_COLL_TOTAL = "dl4j_step_collectives_total"
_COMM_SECONDS = "dl4j_step_comm_seconds"
_COMM_RATIO = "dl4j_step_comm_compute_ratio"
_LINK_BW = "dl4j_link_bandwidth_bytes_per_s"
_SHARDED_BYTES = "dl4j_sharded_bytes"
_REPLICATION = "dl4j_replication_factor"

# ---------------------------------------------------------------- bandwidth
# Per-chip interconnect (ICI) bandwidth, bytes/s, all links combined —
# public spec-sheet figures (v5e: 1,600 Gbps/chip; v5p: 4,800; v4: 2,400;
# v3: 700 per link x 4? the public per-chip figure is 656 Gbps x ...).
# The ONE owner of the table: the comm roofline, the grad-sync CLI and
# bench all import it from here (same single-owner discipline as
# ``profiling.PEAK_FLOPS``).  Values are deliberately round spec numbers;
# every consumer labels the derived seconds as estimates.
LINK_BANDWIDTH = {
    "TPU v6": 448e9,     # Trillium: 3,584 Gbps/chip
    "TPU v5p": 600e9,    # 4,800 Gbps/chip
    "TPU v5": 200e9,     # v5 lite (v5e): 1,600 Gbps/chip
    "TPU v4": 300e9,     # 2,400 Gbps/chip
    "TPU v3": 112e9,     # ~900 Gbps/chip
    "TPU v2": 62e9,      # ~500 Gbps/chip
}

# ESTIMATE: on the virtual host-platform mesh a "collective" is a memcpy
# through shared DRAM; one socket sustains O(10) GB/s effective through
# an XLA:CPU all-reduce.  Order-of-magnitude only — every consumer
# labels CPU-derived comm seconds as an estimate (the honest-labeling
# discipline of ``profiling.CPU_PEAK_FLOPS_ESTIMATE``).
CPU_LINK_BANDWIDTH_ESTIMATE = 10e9


def link_bandwidth_for(device=None) -> Tuple[float, str]:
    """(link bandwidth bytes/s, source) for a jax device (default:
    ``devices()[0]``).  source: ``"table"`` (TPU spec sheet),
    ``"cpu-estimate"`` (documented estimate), or ``"unknown"`` (0.0 —
    comm seconds not computable)."""
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:
            return 0.0, "unknown"
    kind = getattr(device, "device_kind", "") or ""
    for prefix, bw in LINK_BANDWIDTH.items():
        if kind.startswith(prefix):
            return bw, "table"
    if getattr(device, "platform", "") == "cpu":
        return CPU_LINK_BANDWIDTH_ESTIMATE, "cpu-estimate"
    return 0.0, "unknown"


def ring_wire_bytes(op: str, payload_bytes: float,
                    group_size: Optional[int]) -> float:
    """Bytes through each device's link for one collective, ring
    algorithm (the scaling-book recipe ``measure_grad_sync`` uses):
    all-reduce moves ``2(g-1)/g * payload``; all-gather/reduce-scatter
    half that; a permute moves the payload once.  Unknown group size
    falls back to the payload (a lower bound, labeled as such)."""
    g = group_size or 0
    if g < 2:
        return float(payload_bytes)
    if op == "all-reduce":
        return 2.0 * (g - 1) / g * payload_bytes
    if op in ("all-gather", "reduce-scatter"):
        return (g - 1) / g * payload_bytes
    return float(payload_bytes)


# ------------------------------------------------------------------- census
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

# one HLO shape token: dtype[dims]{layout?} — the layout braces may hold
# TPU tile annotations with parens ({0:T(8,128)}), but never nested braces
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?")
# an instruction line: "%name = <shape or (tuple)> <op>(" — the op name
# token directly before the open paren is what defines the instruction
# (operand shapes inside the parens must not match).  The tuple
# alternative must tolerate one level of nested parens: post-layout TPU
# HLO writes tuple results like "(f32[1024]{0:T(1024)}, ...)", and a
# first-)-stops scan would drop exactly the variadic/async collectives
# the census exists to count.
_INSTR_RE = re.compile(
    # single-char inner alternation, NOT "[^()]+": a nested + inside *
    # backtracks exponentially on long non-matching paren runs
    r"=\s*(\((?:[^()]|\([^()]*\))*\)"
    r"|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(" + "|".join(COLLECTIVE_OPS) + r")(-start)?\(")
# iota form: replica_groups=[groups,size]<=[n...] ; explicit form:
# replica_groups={{0,1},{2,3}}
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([0-9, ]+)\}")


def _shape_bytes(token: str) -> int:
    """Bytes of one HLO shape token (or a tuple of them)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(token):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue  # token/opaque types carry no accountable payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def _group_size(line: str) -> Optional[int]:
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(2)) or None
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len([t for t in m.group(1).split(",") if t.strip()])
    return None


def collective_census(hlo_text: str) -> Dict[str, Dict[str, Any]]:
    """Count and size every collective instruction in compiled HLO text.

    Returns ``{op: {"count": n, "bytes": payload_bytes,
    "group_sizes": [...]}}`` — ``bytes`` is the payload (max of result
    and operand bytes, so all-gather counts the gathered tensor and
    reduce-scatter the pre-scatter one), NOT wire bytes (see
    ``ring_wire_bytes``).  Async ``-start`` instructions count once;
    their ``-done`` halves carry no shape work and never match."""
    out: Dict[str, Dict[str, Any]] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.search(line)
        if m is None:
            continue
        result_tok, op = m.group(1), m.group(2)
        # operand list: everything inside the instruction's parens —
        # balanced-paren scan not needed, shapes never nest parens
        operands = line[m.end():line.rfind(")")]
        res_b, opnd_b = _shape_bytes(result_tok), _shape_bytes(operands)
        if m.group(3):
            # async -start: the result is an (operand, result) tuple, so
            # res_b double-counts — the payload is the larger half
            payload = max(res_b - opnd_b, opnd_b)
        else:
            payload = max(res_b, opnd_b)
        entry = out.setdefault(op, {"count": 0, "bytes": 0,
                                    "group_sizes": []})
        entry["count"] += 1
        entry["bytes"] += payload
        g = _group_size(line)
        if g is not None and g not in entry["group_sizes"]:
            entry["group_sizes"].append(g)
    return out


def attribute_mesh_axes(census: Dict[str, Dict[str, Any]],
                        axis_sizes: Dict[str, int]) -> Dict[str, List[str]]:
    """Best-effort mesh-axis attribution: an op whose replica group size
    equals the size of exactly ONE mesh axis is attributed to that axis
    (a 2-D mesh with equal axis sizes stays honest and unattributed)."""
    out: Dict[str, List[str]] = {}
    for op, entry in census.items():
        axes: List[str] = []
        for g in entry.get("group_sizes", ()):
            named = [a for a, s in axis_sizes.items() if s == g]
            if len(named) == 1 and named[0] not in axes:
                axes.append(named[0])
        out[op] = axes
    return out


def program_analysis(fn, args: Tuple, kwargs: Dict, *,
                     cost: bool = True, memory: bool = True,
                     collectives: bool = True) -> Dict[str, Any]:
    """The full per-program accounting at the ABSTRACT signature of
    ``args``/``kwargs`` (every array leaf replaced by a
    ``ShapeDtypeStruct`` — donated buffers never touched, nothing
    executes): XLA cost analysis (flops/bytes — the ONE owner of that
    recipe; ``profiling.jit_cost_analysis`` delegates here, and an
    installed ``StepProfiler`` reads this dict unchanged),
    ``memory_analysis()`` byte kinds, and the collective census of the
    compiled HLO.  The section flags skip work callers don't need
    (``as_text`` on a big program is not free).  ``{}`` when the
    backend supports none of it."""
    import jax
    from jax.sharding import NamedSharding

    def absify(leaf):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return leaf
        # the sharding must ride into the abstract signature: a jit
        # without explicit in_shardings (ParallelWrapper's fit_window)
        # gets its layout from the ARGUMENTS, and lowering without it
        # would compile a collective-free single-device program —
        # exactly the bytes this census exists to count
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.ShapeDtypeStruct(tuple(shape), dtype,
                                        sharding=sharding)
        return jax.ShapeDtypeStruct(tuple(shape), dtype)

    try:
        abs_args, abs_kwargs = jax.tree_util.tree_map(absify, (args, kwargs))
        compiled = fn.lower(*abs_args, **abs_kwargs).compile()
    except Exception:
        return {}
    out: Dict[str, Any] = {}
    if cost:
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            out["flops"] = float(ca.get("flops", 0.0) or 0.0)
            out["bytes_accessed"] = float(
                ca.get("bytes accessed", 0.0) or 0.0)
        except Exception:
            pass
    if memory:
        try:
            ma = compiled.memory_analysis()
            out["memory"] = {
                "argument": int(ma.argument_size_in_bytes),
                "output": int(ma.output_size_in_bytes),
                "temp": int(ma.temp_size_in_bytes),
                "alias": int(ma.alias_size_in_bytes),
                "generated_code": int(ma.generated_code_size_in_bytes),
            }
        except Exception:
            pass
    if collectives:
        try:
            census = collective_census(compiled.as_text())
            out["collectives"] = census
            out["collective_bytes"] = float(
                sum(e["bytes"] for e in census.values()))
            out["collective_count"] = int(
                sum(e["count"] for e in census.values()))
        except Exception:
            pass
    return out


# ------------------------------------------------------------------ ledger
# Reserved updater-state subtrees the ZeRO update sharding keeps
# REPLICATED (stacked per replica in the wrapper): the stability engine's
# guard/scale scalars, the introspection stat vectors, and the numerics
# precision-ledger vector.  Mirrors ``resilience.stability.STATE_KEY`` /
# ``observability.introspection.STATE_KEY`` / ``observability.numerics
# .STATE_KEY`` — literals here so the ledger stays importable without
# jax; ``tests/test_zero.py`` pins the mirror.
RESERVED_REPLICATED_SUBTREES = ("__stability__", "__introspect__",
                                "__numerics__")


def zero_shardable(shape, k: int) -> bool:
    """Whether a leaf of ``shape`` participates in ZeRO update sharding
    over a ``k``-way data axis: its leading dimension must exist and
    divide evenly (a non-dividing leaf stays replicated — padding a
    shard would change the updater's elementwise math for schedules
    that read positions).  The ONE owner of the predicate: the
    projected-ZeRO ledger column and ``parallel.zero``'s actual layout
    both call this, which is what makes the projection testable against
    the real thing."""
    shape = tuple(shape)
    return (k > 1 and len(shape) >= 1 and shape[0] > 0
            and shape[0] % k == 0)


def _projected_zero_bytes(tree, k: int, reserved: bool = False) -> int:
    """Per-device bytes of ONE logical copy of ``tree`` under ZeRO
    update sharding: shardable leaves contribute 1/k of their bytes,
    non-dividing leaves and reserved subtrees (``__stability__`` /
    ``__introspect__``) stay replicated and contribute in full.  Walks
    shape/dtype metadata only."""
    import jax

    total = 0
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        acc = _leaf_accounting(leaf)
        if acc is None:
            continue
        top = getattr(path[0], "key", None) if path else None
        if (reserved or top in RESERVED_REPLICATED_SUBTREES
                or not zero_shardable(getattr(leaf, "shape", ()), k)):
            total += acc["global"]
        else:
            total += -(-acc["global"] // k)          # ceil
    return total


def _leaf_accounting(leaf) -> Optional[Dict[str, Any]]:
    """Shape/dtype/sharding metadata of one leaf — NEVER reads a buffer.
    None for non-array leaves (python scalars ride replicated for free)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return None
    import numpy as np

    try:
        itemsize = np.dtype(dtype).itemsize
    except Exception:
        return None
    global_bytes = int(math.prod(tuple(shape)) * itemsize)
    per_device = global_bytes
    ndev = 1
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shard_shape = sharding.shard_shape(tuple(shape))
            per_device = int(math.prod(shard_shape) * itemsize)
            ndev = int(getattr(sharding, "num_devices", None)
                       or len(sharding.device_set))
        except Exception:
            pass
    return {"global": global_bytes, "per_device": per_device,
            "devices": ndev, "stored": per_device * ndev}


def _tree_row(tree, logical_tree=None,
              data_axis_size: Optional[int] = None,
              reserved: bool = False) -> Dict[str, Any]:
    """One ledger row: aggregate byte accounting of a pytree under its
    actual shardings.  ``logical_tree`` is the SINGLE-MODEL tree when
    ``tree`` is a stacked replica view (ParallelWrapper's [K, ...]
    leaves) — its bytes define the replication denominator; default:
    the tree's own global bytes (right for replicated-sharding layouts,
    where the global array IS one logical copy)."""
    import jax

    glob = per_dev = stored = 0
    ndev = 1
    for leaf in jax.tree_util.tree_leaves(tree):
        acc = _leaf_accounting(leaf)
        if acc is None:
            continue
        glob += acc["global"]
        per_dev += acc["per_device"]
        stored += acc["stored"]
        ndev = max(ndev, acc["devices"])
    logical = glob
    if logical_tree is not None:
        logical = 0
        for leaf in jax.tree_util.tree_leaves(logical_tree):
            acc = _leaf_accounting(leaf)
            if acc is not None:
                logical += acc["global"]
    row: Dict[str, Any] = {
        "logical_bytes": logical,
        "global_bytes": glob,
        "per_device_bytes": per_dev,
        "stored_bytes": stored,
        "devices": ndev,
        "replication_factor": (round(stored / logical, 4) if logical
                               else 1.0),
    }
    k = data_axis_size or ndev
    if logical and k > 1:
        # projected-ZeRO column (arXiv 2004.13336): one logical copy
        # under ZeRO update sharding over the data axis — per LEAF, so
        # non-dividing leaves and the reserved replicated subtrees
        # project at full size exactly as ``parallel.zero`` lays them
        # out (the projection-vs-actual test in tests/test_zero.py
        # holds the two to each other).  Walked over the LOGICAL tree
        # when one is given (the stacked wrapper view's leaves carry a
        # leading replica axis that must not drive the predicate).
        projected = _projected_zero_bytes(
            logical_tree if logical_tree is not None else tree, k,
            reserved=reserved)
        row["zero_projected_per_device_bytes"] = projected
        row["zero_savings_per_device_bytes"] = per_dev - projected
    return row


def sharding_ledger(trees: Dict[str, Any],
                    logical_trees: Optional[Dict[str, Any]] = None,
                    data_axis_size: Optional[int] = None,
                    subtree_depth: int = 1) -> Dict[str, Any]:
    """The ledger over named trees (``{"params": ..., "updater_state":
    ..., "net_state": ...}``): one aggregate row per tree plus rows for
    each top-level subtree (layer / updater slot) so the report answers
    "which subtree is replicated how much" — the per-subtree factor is
    what the ZeRO PR flips for the optimizer moments."""
    logical_trees = logical_trees or {}
    out: Dict[str, Any] = {"trees": {}, "data_axis_size": data_axis_size}
    total = {"logical_bytes": 0, "per_device_bytes": 0, "stored_bytes": 0}
    for name, tree in trees.items():
        if tree is None:
            continue
        logical = logical_trees.get(name)
        row = _tree_row(tree, logical, data_axis_size)
        if subtree_depth > 0 and isinstance(tree, dict):
            subs = {}
            for key, sub in tree.items():
                sub_logical = (logical.get(key)
                               if isinstance(logical, dict) else None)
                subs[str(key)] = _tree_row(
                    sub, sub_logical, data_axis_size,
                    reserved=key in RESERVED_REPLICATED_SUBTREES)
            if subs:
                row["subtrees"] = subs
        out["trees"][name] = row
        for f in total:
            total[f] += row[f]
    total["replication_factor"] = (
        round(total["stored_bytes"] / total["logical_bytes"], 4)
        if total["logical_bytes"] else 1.0)
    out["total"] = total
    return out


def format_ledger(ledger: Dict[str, Any], component: str = "") -> str:
    """Human-readable ledger report (the operator view; JSON stays the
    machine form)."""
    def mb(b):
        return f"{b / 1e6:10.3f}"

    lines = [f"sharding ledger{' — ' + component if component else ''}"
             + (f" (data axis: {ledger.get('data_axis_size')})"
                if ledger.get("data_axis_size") else ""),
             f"{'tree':<28} {'logical MB':>10} {'per-dev MB':>10} "
             f"{'repl':>6} {'ZeRO MB':>10}"]
    for name, row in ledger.get("trees", {}).items():
        zero = row.get("zero_projected_per_device_bytes")
        lines.append(
            f"{name:<28} {mb(row['logical_bytes'])} "
            f"{mb(row['per_device_bytes'])} "
            f"{row['replication_factor']:>6.2f} "
            f"{mb(zero) if zero is not None else '        —'}")
        for sub, srow in (row.get("subtrees") or {}).items():
            szero = srow.get("zero_projected_per_device_bytes")
            lines.append(
                f"  {sub:<26} {mb(srow['logical_bytes'])} "
                f"{mb(srow['per_device_bytes'])} "
                f"{srow['replication_factor']:>6.2f} "
                f"{mb(szero) if szero is not None else '        —'}")
    t = ledger.get("total")
    if t:
        lines.append(
            f"{'TOTAL':<28} {mb(t['logical_bytes'])} "
            f"{mb(t['per_device_bytes'])} {t['replication_factor']:>6.2f}")
    return "\n".join(lines)


# ---------------------------------------------------- ledger store + gauges
_ledger_lock = threading.Lock()
_ledgers: Dict[str, Dict[str, Any]] = {}


def record_ledger(component: str, trees: Dict[str, Any],
                  logical_trees: Optional[Dict[str, Any]] = None,
                  data_axis_size: Optional[int] = None,
                  registry=None,
                  notes: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Compute the ledger, mirror the per-tree rows into
    ``dl4j_sharded_bytes`` / ``dl4j_replication_factor`` gauges, stash
    it for ``latest_ledgers()`` (flight dumps, ``GET /memory``, bench),
    and drop a ``sharding_ledger`` flight event.  O(tree leaves) of
    host metadata work; called at fit entry / device placement — never
    per step.  Best-effort: the fit loops and masters call this
    unguarded on their critical path, so a failure here logs and
    returns ``{}`` instead of aborting training (same contract as the
    flight-dump sections)."""
    try:
        return _record_ledger(component, trees, logical_trees,
                              data_axis_size, registry, notes)
    except Exception:
        logging.getLogger("deeplearning4j_tpu.observability").debug(
            "sharding ledger for %s failed", component, exc_info=True)
        return {}


def _record_ledger(component, trees, logical_trees, data_axis_size,
                   registry, notes=None) -> Dict[str, Any]:
    from deeplearning4j_tpu.observability.metrics import get_registry

    ledger = sharding_ledger(trees, logical_trees, data_axis_size)
    ledger["component"] = str(component)
    if notes:
        # layout provenance (e.g. update_sharding="zero" and which
        # reserved subtrees stayed replicated) — the operator-facing
        # record the ZeRO docs promise
        ledger["notes"] = dict(notes)
    reg = registry if registry is not None else get_registry()
    g_bytes = reg.gauge(
        _SHARDED_BYTES, "Per-device bytes of a tracked pytree under its "
        "actual shardings (ledger row; see docs/observability.md "
        "\"Memory & communication\")", labels=("component", "tree"))
    g_repl = reg.gauge(
        _REPLICATION, "Replication factor of a tracked pytree: bytes "
        "stored across all devices / bytes of one logical copy (K for "
        "K-replica replicated data parallel, ~1 under "
        "update_sharding='zero')", labels=("component", "tree"))
    for name, row in ledger["trees"].items():
        g_bytes.set(row["per_device_bytes"], component=component, tree=name)
        g_repl.set(row["replication_factor"], component=component, tree=name)
    with _ledger_lock:
        _ledgers[str(component)] = ledger
    from deeplearning4j_tpu.observability.flightrecorder import (
        get_flight_recorder,
    )

    get_flight_recorder().record(
        "sharding_ledger", component=component,
        data_axis_size=data_axis_size,
        total_per_device_bytes=ledger["total"]["per_device_bytes"],
        replication_factor=ledger["total"]["replication_factor"])
    return ledger


def record_model_ledger(net, component: str,
                        data_axis_size: Optional[int] = None,
                        registry=None) -> Dict[str, Any]:
    """Ledger of a model facade's params / updater state / net state —
    the one-call form the fit loops use."""
    return record_ledger(
        component,
        {"params": getattr(net, "params", None),
         "updater_state": getattr(net, "updater_state", None),
         "net_state": getattr(net, "net_state", None)},
        data_axis_size=data_axis_size, registry=registry)


def latest_ledgers() -> Dict[str, Dict[str, Any]]:
    """Most recent ledger per component (for flight dumps, the UI
    ``GET /memory`` endpoint, and the bench memory section)."""
    with _ledger_lock:
        return dict(_ledgers)


def clear_ledgers() -> None:
    """Test isolation."""
    with _ledger_lock:
        _ledgers.clear()


# --------------------------------------------------------------- collector
class ShardStatsCollector:
    """Per-program memory + collective accounting, harvested through the
    ``RecompileDetector.check(cost_fn=)`` seam.

    Usage::

        coll = ShardStatsCollector().install()
        net.fit(batches)        # census + memory gauges fill per program
        print(coll.programs())  # {fn: {memory, collectives, comm_*}}
        coll.uninstall()

    or as a context manager.  While installed, every ``instrument``-
    wrapped jitted function is analyzed ONCE per new abstract signature
    (``program_analysis`` — abstract lowering, donation-safe) and every
    dispatch bumps the collective counters from the cached census.  The
    analysis dict includes the ``jit_cost_analysis`` fields, so a
    concurrently installed ``StepProfiler`` keeps its MFU attribution
    from the same single lower+compile.
    """

    def __init__(self, registry=None, link_bandwidth: Optional[float] = None,
                 peak_flops: Optional[float] = None):
        from deeplearning4j_tpu.observability.metrics import get_registry
        from deeplearning4j_tpu.observability.profiling import peak_flops_for

        reg = registry if registry is not None else get_registry()
        self._registry = reg
        if link_bandwidth is not None:
            self.link_bandwidth, self.link_source = (float(link_bandwidth),
                                                     "override")
        else:
            self.link_bandwidth, self.link_source = link_bandwidth_for()
        if peak_flops is not None:
            self.peak_flops = float(peak_flops)
        else:
            self.peak_flops, _src = peak_flops_for()
        self._m_mem = reg.gauge(
            _PROGRAM_MEMORY, "Compiled-program memory_analysis() bytes per "
            "jitted function (kind: argument / output / temp / alias / "
            "generated_code), refreshed once per abstract signature",
            labels=("fn", "kind"))
        self._m_coll_bytes = reg.counter(
            _COLL_BYTES, "Collective payload bytes dispatched per jitted "
            "function and HLO op (census of the compiled program, counted "
            "once per call; collectives inside scan/while bodies are "
            "counted once per dispatch, not per trip)",
            labels=("fn", "op"))
        self._m_coll_total = reg.counter(
            _COLL_TOTAL, "Collective instructions dispatched per jitted "
            "function and HLO op (same census/caveats as "
            "dl4j_step_collective_bytes)", labels=("fn", "op"))
        self._m_comm_s = reg.gauge(
            _COMM_SECONDS, "Estimated communication seconds per step of "
            "the current compiled program: ring wire bytes over the "
            "backend link bandwidth (spec table on TPU, documented "
            "estimate on CPU)", labels=("fn",))
        self._m_ratio = reg.gauge(
            _COMM_RATIO, "Estimated comm/compute ratio of the current "
            "compiled program: comm seconds (link-bandwidth roofline) / "
            "compute seconds (flops over peak); > 1 means the step is "
            "communication-bound", labels=("fn",))
        self._m_bw = reg.gauge(
            _LINK_BW, "Link bandwidth assumed by the comm roofline "
            "(spec-sheet table for TPUs; on CPU a documented "
            "order-of-magnitude estimate)", labels=("source",))
        self._lock = threading.Lock()
        # fn -> {id(analysis dict): [(counter child, amount), ...]} —
        # the per-dispatch fast path is a dict-identity lookup + cached
        # incs.  Keyed per analysis id, not one slot per fn: a function
        # alternating between two live signatures (full batch /
        # remainder batch) must not re-absorb on every flip.  Bounded by
        # the detector's per-signature cost cache, which keeps the
        # analysis dicts (and so their ids) alive.
        self._dispatch_cache: Dict[str, Dict[int, List]] = {}
        self._programs: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------ lifecycle
    def install(self) -> "ShardStatsCollector":
        global _active
        self._m_bw.set(self.link_bandwidth, source=self.link_source)
        _active = self
        return self

    def uninstall(self) -> None:
        global _active
        if _active is self:
            _active = None

    def __enter__(self) -> "ShardStatsCollector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -------------------------------------------------------------- harvest
    def note_dispatch(self, fn_name: str, analysis: Optional[Dict]) -> None:
        """Called by ``recompile._InstrumentedJit`` per call with the
        dispatched signature's cached ``program_analysis`` dict.  First
        sight of a dict refreshes the program gauges; every call bumps
        the collective counters from the cached census."""
        if not analysis or ("collectives" not in analysis
                            and "memory" not in analysis):
            # a flops-only dict (profiler-era signature analyzed before
            # this collector was installed) carries NO census: absorbing
            # it would report a confidently wrong zero for a program
            # that may all-reduce megabytes — absent beats wrong
            return
        key = id(analysis)
        # dl4jlint: disable-next-line=lock-discipline -- GIL-atomic dict read on the dispatch fast path; a racing writer at worst causes one redundant _absorb of the same analysis (gauge re-set, idempotent)
        cached = self._dispatch_cache.get(fn_name)
        if cached is None or key not in cached:
            incs = self._absorb(fn_name, analysis)
            with self._lock:
                cached = dict(self._dispatch_cache.get(fn_name) or {})
                cached[key] = incs
                self._dispatch_cache[fn_name] = cached
        for child, amount in cached[key]:
            child.inc(amount)

    def _absorb(self, fn_name: str, analysis: Dict) -> List:
        """Signature-change slow path: set the program gauges, compute
        the roofline, and build the per-dispatch increment list."""
        incs: List = []
        for kind, b in (analysis.get("memory") or {}).items():
            self._m_mem.set(b, fn=fn_name, kind=kind)
        census = analysis.get("collectives") or {}
        wire = 0.0
        for op, entry in census.items():
            incs.append((self._m_coll_bytes.labels(fn=fn_name, op=op),
                         float(entry["bytes"])))
            incs.append((self._m_coll_total.labels(fn=fn_name, op=op),
                         float(entry["count"])))
            gs = entry.get("group_sizes") or [None]
            # one group size per op in practice; a mixed-size variadic
            # op uses the first recovered size for the ring factor
            wire += ring_wire_bytes(op, entry["bytes"], gs[0])
        comm_s = (wire / self.link_bandwidth if self.link_bandwidth > 0
                  else None)
        flops = analysis.get("flops") or 0.0
        compute_s = (flops / self.peak_flops
                     if flops > 0 and self.peak_flops > 0 else None)
        if comm_s is not None:
            self._m_comm_s.set(comm_s, fn=fn_name)
        ratio = None
        if comm_s is not None and compute_s:
            ratio = comm_s / compute_s
            self._m_ratio.set(ratio, fn=fn_name)
        with self._lock:
            self._programs[fn_name] = {
                "memory": analysis.get("memory"),
                "collectives": census,
                "collective_bytes": analysis.get("collective_bytes", 0.0),
                "collective_count": analysis.get("collective_count", 0),
                "wire_bytes_per_device": wire,
                "comm_seconds_estimate": comm_s,
                "compute_seconds_estimate": compute_s,
                "comm_compute_ratio": ratio,
                "flops": analysis.get("flops"),
            }
        return incs

    def analyze_program(self, fn, name: str, args: Tuple,
                        kwargs: Optional[Dict] = None) -> Dict[str, Any]:
        """Analyze a jitted callable OUTSIDE the instrument seam (the
        generation warmup and the grad-sync CLI own raw ``jax.jit``
        objects): runs ``program_analysis`` at the abstract signature
        and absorbs the result under ``name`` (gauges set, census
        cached; per-dispatch counters are the caller's to bump via
        ``note_dispatch`` if it dispatches repeatedly)."""
        analysis = program_analysis(fn, tuple(args), dict(kwargs or {}))
        if analysis:
            incs = self._absorb(name, analysis)   # takes the lock itself
            with self._lock:
                self._dispatch_cache[name] = {id(analysis): incs}
        return analysis

    def programs(self) -> Dict[str, Dict[str, Any]]:
        """Per-function accounting snapshot (bench memory section and
        ``GET /memory``)."""
        with self._lock:
            return {k: dict(v) for k, v in self._programs.items()}


_active: Optional[ShardStatsCollector] = None


def active_collector() -> Optional[ShardStatsCollector]:
    """The installed collector, or None (lock-free read: module-global
    assignment is atomic)."""
    return _active
