"""Fit-loop telemetry handles shared by both model facades.

One ``FitTelemetry`` per model kind (MultiLayerNetwork / ComputationGraph),
cached at module level so the hot loop does a dict lookup + a few metric
updates per iteration and the facades never hold registry objects (keeps
them trivially copyable/serializable).  The score gauge stores the
*on-device* loss scalar — the ``LazyScoreMixin`` contract — so recording it
costs no device->host sync; the transfer happens at scrape time.

Metric names (see docs/observability.md):

- ``dl4j_fit_iterations_total{model=}``    counter
- ``dl4j_fit_step_seconds{model=}``        histogram (host wall time around
  the step dispatch — on TPU this is dispatch+queue time, the number the
  async hot loop actually pays per step)
- ``dl4j_fit_last_step_seconds{model=}``   gauge
- ``dl4j_fit_samples_per_second{model=}``  gauge
- ``dl4j_fit_batch_size{model=}``          gauge
- ``dl4j_fit_score{model=}``               gauge (lazy device scalar)
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.observability.metrics import (
    MetricsRegistry, get_registry,
)
from deeplearning4j_tpu.observability.tracing import get_tracer


class FitTelemetry:
    """Pre-resolved metric children for one model kind."""

    __slots__ = ("model_kind", "iterations", "step_seconds", "last_step",
                 "samples_per_sec", "batch_size", "score")

    def __init__(self, model_kind: str, registry: MetricsRegistry):
        self.model_kind = model_kind
        lab = dict(model=model_kind)
        self.iterations = registry.counter(
            "dl4j_fit_iterations_total",
            "Training iterations completed by the fit loop",
            labels=("model",)).labels(**lab)
        self.step_seconds = registry.histogram(
            "dl4j_fit_step_seconds",
            "Per-iteration host wall time around the train-step dispatch",
            labels=("model",)).labels(**lab)
        self.last_step = registry.gauge(
            "dl4j_fit_last_step_seconds",
            "Most recent iteration's step time",
            labels=("model",)).labels(**lab)
        self.samples_per_sec = registry.gauge(
            "dl4j_fit_samples_per_second",
            "Throughput implied by the most recent step",
            labels=("model",)).labels(**lab)
        self.batch_size = registry.gauge(
            "dl4j_fit_batch_size",
            "Most recent minibatch size seen by the fit loop",
            labels=("model",)).labels(**lab)
        self.score = registry.gauge(
            "dl4j_fit_score",
            "Most recent training loss (lazy device scalar; synced at "
            "scrape)", labels=("model",)).labels(**lab)

    def span(self, iteration: int):
        """Per-iteration span (parent/child nesting handled by the
        tracer)."""
        return get_tracer().span("fit_step", model=self.model_kind,
                                 iteration=iteration)

    def record_step(self, dt_s: float, batch: Optional[int],
                    score: Any, steps: int = 1, model: Any = None) -> None:
        """Record one fit-loop dispatch.  ``score`` may be an on-device
        scalar (stored lazily).  ``steps`` > 1 for scanned windows where
        one dispatch carries several weight updates.  When ``model`` is
        given, the per-step time and throughput are also stamped on it
        (``last_step_seconds`` / ``last_samples_per_second``) so consumers
        holding the model (``ui.stats.StatsListener``) read timing that is
        identity-correct — the registry gauges below are keyed by model
        KIND and would cross-contaminate two same-class models."""
        self.iterations.inc(steps)
        per = dt_s / max(1, steps)
        self.step_seconds.observe(per)
        self.last_step.set(per)
        sps = (batch * steps / dt_s) if (batch and dt_s > 0) else None
        if batch:
            self.batch_size.set(batch)
            if sps is not None:
                self.samples_per_sec.set(sps)
        if score is not None:
            self.score.set(score)
        if model is not None:
            model.last_step_seconds = per
            if sps is not None:
                model.last_samples_per_second = sps


_lock = threading.Lock()
_cache: Dict[str, Tuple[MetricsRegistry, FitTelemetry]] = {}


def fit_telemetry(model_kind: str) -> FitTelemetry:
    """Cached handle for the current global registry; rebuilt transparently
    when tests swap the registry via ``set_registry`` AND when the same
    registry is wiped via ``reset()`` (a stale handle would keep writing
    into orphaned children that no export can see)."""
    reg = get_registry()
    with _lock:
        hit = _cache.get(model_kind)
        if (hit is not None and hit[0] is reg
                and reg.get("dl4j_fit_iterations_total") is not None):
            return hit[1]
        tel = FitTelemetry(model_kind, reg)
        _cache[model_kind] = (reg, tel)
        return tel
