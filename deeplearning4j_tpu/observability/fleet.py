"""Fleet telemetry plane: cross-process metrics federation + decode SLOs.

Every telemetry consumer so far (``/metrics``, ``/health``,
``HealthEvaluator``, the bench snapshots) reads one in-process
``MetricsRegistry``.  Multi-host training and an N-replica serving fleet
(ROADMAP item 5) need one process to see N: this module makes registry
state travel.

- ``TelemetryPublisher`` serializes a bounded, schema-versioned snapshot
  of the local registry (counters as monotonic totals, gauges, histogram
  bucket arrays) plus the local health verdict, arbitrary worker state,
  the prefix-cache stats surface, and the SLO tracker onto a
  ``MessageBroker`` topic at a configurable interval.  Snapshots carry a
  per-process ``epoch`` (fresh UUID per publisher) and a monotonically
  increasing ``seq`` so the aggregator can merge counters delta-safely
  across publisher restarts.
- ``FleetAggregator`` subscribes (local broker or the broker's HTTP
  long-poll transport), merges per-worker snapshots into a
  worker-labeled fleet registry, marks workers whose snapshots stop
  arriving as STALE after ``expire_after_s`` (their gauges are dropped
  rather than frozen-healthy; counters and histograms — being monotonic
  history — persist), and serves fleet-level ``GET /metrics``,
  ``GET /fleet`` (per-worker table + staleness + the router-facing
  prefix-cache stats), and a fleet-scoped ``GET /health``.
- ``SLOTracker`` computes TTFT- and ITL-attainment fractions against
  configurable targets plus goodput (requests/sec meeting BOTH SLOs,
  rolling window) — the decode-quality number a router places on.

Counter-epoch merge rules (documented in docs/observability.md "Fleet
telemetry"):  within one epoch, the merged total advances by
``new_total - last_total`` and replayed/reordered sequence numbers are
dropped; a NEW epoch (publisher restart) contributes its full totals on
top of the history already merged — no double-count, and no
reset-to-zero artifact.  Histograms merge the same way on their
(count, bucket_counts) arrays.

This module must stay importable without jax or numpy: the CI schema
round-trip gate (``scripts/ci_checks.py`` runs
``schema_roundtrip_selftest`` in a bare subprocess) loads it where
heavyweight imports would swamp the check.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.observability.health import (
    HealthEvaluator, HealthRule, HealthVerdict,
)
from deeplearning4j_tpu.observability.metrics import (
    DEFAULT_BUCKETS, MetricsRegistry, get_registry,
)

SCHEMA_VERSION = 1
DEFAULT_TOPIC = "fleet.telemetry"

_GOODPUT = "dl4j_decode_goodput_rps"
_ATTAIN = "dl4j_decode_slo_attainment"
_WORKERS = "dl4j_fleet_workers"
_STALE = "dl4j_fleet_stale_workers"
_AGE = "dl4j_fleet_snapshot_age_seconds"
_SNAPSHOTS = "dl4j_fleet_snapshots_total"
_SKIPS = "dl4j_fleet_merge_skips_total"
_LAG = "dl4j_fleet_ingest_lag_seconds"
_PUBLISH = "dl4j_fleet_publish_seconds"
_BYTES = "dl4j_fleet_snapshot_bytes"

_H_SNAPSHOTS = ("Telemetry snapshots merged into the fleet view, per "
                "publishing worker")
_H_SKIPS = ("Snapshots or snapshot fragments the aggregator dropped "
            "instead of raising (reason: parse/schema/fields/replay/"
            "family/export)")

logger = logging.getLogger("deeplearning4j_tpu.observability")

_WARN_INTERVAL_S = 30.0


def _finite(v: Any) -> Optional[float]:
    """float(v) if finite else None — NaN/Inf gauges must not poison a
    strict-JSON snapshot (json.dumps(allow_nan=False))."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def _num(v: Any) -> Optional[float]:
    """A finite number from the wire, or None (bools excluded)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v) if math.isfinite(v) else None


def _quantile(values: Sequence[float], q: float) -> float:
    vs = sorted(values)
    if not vs:
        return float("nan")
    pos = q * (len(vs) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)


class _RateLimitedWarn:
    """One warning per key per _WARN_INTERVAL_S — a wedged peer must not
    turn the log into a firehose."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last: Dict[str, float] = {}

    def __call__(self, key: str, msg: str) -> None:
        now = time.monotonic()
        with self._lock:
            last = self._last.get(key)
            if last is not None and now - last < _WARN_INTERVAL_S:
                return
            self._last[key] = now
        logger.warning(msg)


# -------------------------------------------------------------------- SLOs
class SLOTracker:
    """Per-request SLO attainment + goodput for one generation engine.

    A finished request is GOOD when it completed normally AND its TTFT is
    within ``ttft_target_s`` AND the p95 of its inter-token latencies is
    within ``itl_target_s`` (a request short enough to have no
    inter-token gaps passes the ITL leg vacuously).  Goodput is good
    requests per second over a rolling ``goodput_window_s`` window —
    the TTFT/TBT goodput framing of continuous-batching serving.

    Owns the ``dl4j_decode_goodput_rps{engine}`` and
    ``dl4j_decode_slo_attainment{engine,slo}`` gauge families (lazy:
    resolved at scrape time, nothing on the decode hot path).
    """

    def __init__(self, ttft_target_s: float = 0.2,
                 itl_target_s: float = 0.05,
                 goodput_window_s: float = 30.0,
                 registry: Optional[MetricsRegistry] = None,
                 engine_id: str = "engine"):
        reg = registry if registry is not None else get_registry()
        self.ttft_target_s = float(ttft_target_s)
        self.itl_target_s = float(itl_target_s)
        self.goodput_window_s = float(goodput_window_s)
        self.engine_id = str(engine_id)
        self._lock = threading.Lock()
        self.finished = 0
        self.ttft_met = 0
        self.itl_met = 0
        self.good_total = 0
        self._good_times: deque = deque()
        reg.gauge(
            _GOODPUT, "Requests per second finishing while meeting BOTH "
            "the TTFT and inter-token-latency SLO targets (rolling "
            "window)", labels=("engine",)
        ).set_function(self.goodput_rps, engine=self.engine_id)
        attain = reg.gauge(
            _ATTAIN, "Fraction of finished generation requests meeting "
            "the labeled SLO leg (ttft | itl | both) against the "
            "configured targets", labels=("engine", "slo"))
        attain.set_function(self.ttft_attainment,
                            engine=self.engine_id, slo="ttft")
        attain.set_function(self.itl_attainment,
                            engine=self.engine_id, slo="itl")
        attain.set_function(self.good_attainment,
                            engine=self.engine_id, slo="both")

    def observe_request(self, *, ttft_s: Optional[float],
                        itl_s: Optional[Sequence[float]] = None,
                        completed: bool = True,
                        now: Optional[float] = None) -> bool:
        """Record one finished request; returns whether it was good."""
        itl = [float(x) for x in (itl_s or ())]
        ttft_ok = ttft_s is not None and float(ttft_s) <= self.ttft_target_s
        itl_ok = (not itl) or _quantile(itl, 0.95) <= self.itl_target_s
        good = bool(completed) and ttft_ok and itl_ok
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            self.finished += 1
            if ttft_ok:
                self.ttft_met += 1
            if itl_ok:
                self.itl_met += 1
            if good:
                self.good_total += 1
                self._good_times.append(now)
            self._prune(now)
        return good

    def _prune(self, now: float) -> None:
        cutoff = now - self.goodput_window_s
        # every caller (observe_request, goodput_rps) holds self._lock
        # dl4jlint: disable-next-line=lock-discipline -- callers hold _lock
        while self._good_times and self._good_times[0] < cutoff:
            self._good_times.popleft()

    def goodput_rps(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            self._prune(now)
            return len(self._good_times) / self.goodput_window_s

    def _frac(self, attr: str) -> float:
        with self._lock:
            met = getattr(self, attr)
            return met / self.finished if self.finished else float("nan")

    def ttft_attainment(self) -> float:
        return self._frac("ttft_met")

    def itl_attainment(self) -> float:
        return self._frac("itl_met")

    def good_attainment(self) -> float:
        return self._frac("good_total")

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe summary (rides in the federated snapshot)."""
        with self._lock:
            finished = self.finished
            good = self.good_total
            ttft_met, itl_met = self.ttft_met, self.itl_met
        return {
            "targets": {"ttft_s": self.ttft_target_s,
                        "itl_p95_s": self.itl_target_s,
                        "goodput_window_s": self.goodput_window_s},
            "finished": finished,
            "good_total": good,
            "ttft_attainment": ttft_met / finished if finished else None,
            "itl_attainment": itl_met / finished if finished else None,
            "good_attainment": good / finished if finished else None,
            "goodput_rps": self.goodput_rps(),
        }


# -------------------------------------------------------------- publisher
class TelemetryPublisher:
    """Publishes bounded, schema-versioned registry snapshots to a topic.

    Transport is the existing ``MessageBroker``: pass ``broker=`` for an
    in-process broker or ``url=`` for a remote one exposed via
    ``MessageBroker.serve()`` (POST ``/publish/<topic>``).  With neither,
    ``snapshot()`` still works (tests, bench probes).

    Reads ONLY host-side state: counters/gauges/histograms are plain
    Python numbers, prefix-cache stats and the SLO tracker are host
    dicts, and lazy gauges holding device scalars resolve through the
    registry's scrape-time ``float()`` exactly like ``/metrics`` does —
    publishing never adds a device->host sync to the decode loop.
    """

    def __init__(self, worker_id: str, *, broker=None,
                 url: Optional[str] = None, topic: str = DEFAULT_TOPIC,
                 interval_s: float = 2.0,
                 registry: Optional[MetricsRegistry] = None,
                 health: Optional[HealthEvaluator] = None,
                 state_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 prefix_cache=None,
                 slo: Optional[SLOTracker] = None,
                 max_samples_per_family: int = 64,
                 timeout: float = 5.0, retry_policy=None):
        if broker is not None and url is not None:
            raise ValueError("pass broker= or url=, not both")
        self.worker_id = str(worker_id)
        self.broker = broker
        self.url = url.rstrip("/") if url else None
        self.topic = topic
        self.interval_s = float(interval_s)
        self.timeout = float(timeout)
        self.health = health
        self.state_fn = state_fn
        self.prefix_cache = prefix_cache
        self.slo = slo
        self.max_samples_per_family = int(max_samples_per_family)
        self.epoch = uuid.uuid4().hex[:12]
        self.seq = 0
        self._registry = registry
        self._warn = _RateLimitedWarn()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = registry if registry is not None else get_registry()
        self._m_publish = reg.histogram(
            _PUBLISH, "Wall time to serialize and publish one telemetry "
            "snapshot")
        self._m_bytes = reg.gauge(
            _BYTES, "Serialized size of the most recently published "
            "telemetry snapshot")
        # publish-loop hygiene (PR-5 RetryPolicy): a transient broker /
        # aggregator outage backs off and resumes instead of warning
        # every period — load-bearing now that the fleet router reads
        # snapshot liveness as a membership signal.  Backoff sleeps go
        # through the stop event so stop() never waits out a retry.
        if retry_policy is None:
            from deeplearning4j_tpu.resilience.retry import RetryPolicy
            retry_policy = RetryPolicy(
                max_retries=3, base_delay_s=min(0.25, self.interval_s),
                max_delay_s=max(2.0, self.interval_s),
                component="telemetry", registry=reg,
                sleep=self._stop.wait)
        self.retry_policy = retry_policy

    # ------------------------------------------------------------ snapshot
    def _prefix_cache_stats(self) -> Optional[Dict[str, Any]]:
        pc = self.prefix_cache
        if pc is None:
            return None
        try:
            stats = pc() if callable(pc) else pc.stats()
        except Exception as e:
            self._warn("pc", f"prefix-cache stats failed: {e!r}")
            return None
        return stats if isinstance(stats, dict) else None

    def snapshot(self) -> Dict[str, Any]:
        """One bounded, JSON-safe view of the local telemetry state."""
        reg = self._registry if self._registry is not None else get_registry()
        self.seq += 1
        health = None
        if self.health is not None:
            try:
                # evaluate FIRST so the mirrored dl4j_health_status gauge
                # in the registry walk below is this verdict, not the last
                health = self.health.evaluate().to_dict()
            except Exception as e:
                self._warn("health", f"health evaluation failed: {e!r}")
        state = None
        if self.state_fn is not None:
            try:
                state = self.state_fn()
                if not isinstance(state, dict):
                    state = None
            except Exception as e:
                self._warn("state", f"state_fn failed: {e!r}")
        families: Dict[str, Any] = {}
        truncated = 0
        for fam in reg.families():
            pairs = fam.samples()
            if len(pairs) > self.max_samples_per_family:
                truncated += len(pairs) - self.max_samples_per_family
                pairs = pairs[:self.max_samples_per_family]
            samples = []
            for label_pairs, child in pairs:
                labels = {str(k): str(v) for k, v in label_pairs}
                if fam.kind == "histogram":
                    hs = child.snapshot()
                    samples.append({
                        "labels": labels,
                        "count": int(hs["count"]),
                        "sum": _finite(hs["sum"]) or 0.0,
                        "min": _finite(hs["min"]),
                        "max": _finite(hs["max"]),
                        "bucket_counts": [int(c) for c in
                                          hs["bucket_counts"]],
                    })
                else:
                    samples.append({"labels": labels,
                                    "value": _finite(child.value)})
            if not samples:
                continue
            fd: Dict[str, Any] = {
                "kind": fam.kind, "help": fam.help,
                "label_names": list(fam.label_names),
                "samples": samples,
            }
            if fam.kind == "histogram":
                fd["buckets"] = [float(b) for b in fam._buckets]
            families[fam.name] = fd
        snap: Dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "worker": self.worker_id,
            "epoch": self.epoch,
            "seq": self.seq,
            "ts": time.time(),
            "families": families,
        }
        if truncated:
            snap["truncated_samples"] = truncated
        if health is not None:
            snap["health"] = health
        if state is not None:
            snap["state"] = state
        pc = self._prefix_cache_stats()
        if pc is not None:
            snap["prefix_cache"] = pc
        if self.slo is not None:
            snap["slo"] = self.slo.as_dict()
        return snap

    # ------------------------------------------------------------- publish
    def serialize(self) -> str:
        """Deterministic wire form (sorted keys; NaN already mapped to
        null by the snapshot walk, so the strict encoder never trips)."""
        return json.dumps(self.snapshot(), sort_keys=True, allow_nan=False)

    def _send(self, payload: str) -> int:
        """Raw transport send; raises on failure."""
        if self.broker is not None:
            return self.broker.publish(self.topic, payload)
        if self.url is not None:
            import urllib.request

            req = urllib.request.Request(
                f"{self.url}/publish/{self.topic}",
                data=payload.encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                return int(json.loads(resp.read().decode()
                                      or '{"delivered": 0}')
                           .get("delivered", 0))
        return 0

    def publish_once(self) -> int:
        """Serialize + publish one snapshot; delivered-subscriber count
        (HTTP: the broker's count), -1 on any failure — the decode/train
        loop must never die because telemetry could not flush."""
        t0 = time.perf_counter()
        try:
            payload = self.serialize()
        except Exception as e:
            self._warn("snapshot", f"snapshot serialization failed: {e!r}")
            return -1
        self._m_bytes.set(float(len(payload)))
        try:
            return self._send(payload)
        except Exception as e:
            self._warn("publish", f"telemetry publish failed: {e!r}")
            return -1
        finally:
            self._m_publish.observe(time.perf_counter() - t0)

    def _publish_strict(self) -> int:
        """``publish_once`` minus the swallow, for the retrying publish
        loop: serialization failures raise AS-IS (a snapshot that cannot
        serialize is a deterministic bug — fatal to the RetryPolicy, so
        it surfaces instead of backing off), transport outages raise
        ``TransientError`` (including broker-side 5xx, which the message
        classification alone would call fatal)."""
        from deeplearning4j_tpu.resilience.retry import (
            TransientError, is_transient)

        t0 = time.perf_counter()
        payload = self.serialize()
        self._m_bytes.set(float(len(payload)))
        try:
            return self._send(payload)
        except Exception as e:
            code = getattr(e, "code", None)
            if is_transient(e) or (isinstance(code, int) and code >= 500):
                raise TransientError(
                    f"telemetry publish failed: {e!r}") from e
            raise
        finally:
            self._m_publish.observe(time.perf_counter() - t0)

    def start(self) -> "TelemetryPublisher":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"telemetry-pub-{self.worker_id}",
            daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        # first snapshot immediately, not after a wait; every period then
        # rides the RetryPolicy — transient outages back off (stop-event
        # interruptible) and resume, anything past the retry budget (or
        # fatal outright) surfaces once per warn interval and the loop
        # carries on at the next period
        first = True
        while True:
            if not first and self._stop.wait(self.interval_s):
                return
            first = False
            if self._stop.is_set():
                return
            try:
                self.retry_policy.run(self._publish_strict,
                                      description="telemetry publish",
                                      context={"worker": self.worker_id})
            except Exception as e:
                self._warn("publish",
                           f"telemetry publish failed after retries: {e!r}")

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=self.timeout + 5.0)
        self._thread = None


# ------------------------------------------------------------- aggregator
class _WorkerView:
    """Merged state for one publishing worker."""

    __slots__ = ("worker", "epoch", "seq", "last_recv", "last_ts",
                 "snapshots", "truncated", "meta", "counters",
                 "counter_last", "hists", "hist_last", "gauges",
                 "health", "state", "prefix_cache", "slo")

    def __init__(self, worker: str):
        self.worker = worker
        self.epoch: Optional[str] = None
        self.seq = 0
        self.last_recv = time.monotonic()
        self.last_ts: Optional[float] = None
        self.snapshots = 0
        self.truncated = 0
        # family -> {"kind","help","label_names","buckets"}
        self.meta: Dict[str, Dict[str, Any]] = {}
        # family -> {label_values_tuple -> merged cumulative total}
        self.counters: Dict[str, Dict[Tuple[str, ...], float]] = {}
        # family -> {key -> last raw total seen in the CURRENT epoch}
        self.counter_last: Dict[str, Dict[Tuple[str, ...], float]] = {}
        self.hists: Dict[str, Dict[Tuple[str, ...], Dict[str, Any]]] = {}
        self.hist_last: Dict[str, Dict[Tuple[str, ...], Dict[str, Any]]] = {}
        self.gauges: Dict[str, Dict[Tuple[str, ...], float]] = {}
        self.health: Optional[Dict[str, Any]] = None
        self.state: Optional[Dict[str, Any]] = None
        self.prefix_cache: Optional[Dict[str, Any]] = None
        self.slo: Optional[Dict[str, Any]] = None


class FleetAggregator:
    """Merges per-worker telemetry snapshots into one fleet view.

    Ingest is forward-compatible by construction: unparseable messages,
    unknown schema versions, missing fields, and malformed family
    fragments are counted in ``dl4j_fleet_merge_skips_total{reason}``
    and logged (rate-limited) — never raised.  Unknown EXTRA keys are
    ignored, so newer publishers can talk to an older aggregator.

    The fleet registry is rebuilt from the merged books on every read
    (``registry()``): each worker's families come back worker-labeled,
    STALE workers (no snapshot for ``expire_after_s``) contribute their
    monotonic counters/histograms but NOT their gauges — a dead worker
    must never look frozen-healthy.  Families that already declare a
    ``worker`` label keep it and gain an ``origin`` label instead.
    """

    FLEET_LABEL = "worker"

    def __init__(self, *, broker=None, url: Optional[str] = None,
                 topic: str = DEFAULT_TOPIC, expire_after_s: float = 10.0,
                 rules: Sequence[HealthRule] = (),
                 min_workers: int = 0,
                 registry: Optional[MetricsRegistry] = None,
                 timeout: float = 5.0):
        if broker is not None and url is not None:
            raise ValueError("pass broker= or url=, not both")
        self.broker = broker
        self.url = url.rstrip("/") if url else None
        self.topic = topic
        self.expire_after_s = float(expire_after_s)
        self.rules = list(rules)
        self.min_workers = int(min_workers)
        self.timeout = float(timeout)
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerView] = {}
        self._warn = _RateLimitedWarn()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._queue = None
        self._sub_id = uuid.uuid4().hex[:8]
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None
        reg = registry if registry is not None else get_registry()
        self._m_snapshots = reg.counter(_SNAPSHOTS, _H_SNAPSHOTS,
                                        labels=("worker",))
        self._m_skips = reg.counter(_SKIPS, _H_SKIPS, labels=("reason",))
        self._m_lag = reg.histogram(
            _LAG, "Wall-clock delay between a snapshot's publish "
            "timestamp and its ingestion by the fleet aggregator")
        self._skips: Dict[str, int] = {}

    # -------------------------------------------------------------- ingest
    def _skip(self, reason: str, detail: str = "") -> None:
        with self._lock:
            self._skips[reason] = self._skips.get(reason, 0) + 1
        self._m_skips.inc(reason=reason)
        if detail:
            self._warn(f"skip:{reason}",
                       f"fleet snapshot dropped ({reason}): {detail}")

    def ingest(self, message: str) -> bool:
        """Merge one wire snapshot; False (never an exception) on drop."""
        try:
            snap = json.loads(message)
        except Exception as e:
            self._skip("parse", repr(e))
            return False
        if not isinstance(snap, dict):
            self._skip("parse", f"non-object snapshot: {type(snap).__name__}")
            return False
        if snap.get("schema") != SCHEMA_VERSION:
            self._skip("schema",
                       f"schema={snap.get('schema')!r} from "
                       f"worker={snap.get('worker')!r}, "
                       f"want {SCHEMA_VERSION}")
            return False
        worker = snap.get("worker")
        if not worker or not isinstance(worker, str):
            self._skip("fields", "snapshot without a worker id")
            return False
        epoch = str(snap.get("epoch") or "")
        seq_n = _num(snap.get("seq"))
        if seq_n is None:
            # defaulting would pin the worker at seq 0 and drop every
            # later same-epoch snapshot as a replay
            self._skip("fields",
                       f"snapshot from {worker!r} without a numeric seq")
            return False
        seq = int(seq_n)
        now = time.monotonic()
        # skips found under the lock are emitted after release: _skip
        # re-acquires self._lock, so calling it here would deadlock
        pending_skips: List[Tuple[str, str]] = []
        with self._lock:
            ws = self._workers.get(worker)
            if ws is None:
                ws = self._workers[worker] = _WorkerView(worker)
            if epoch == ws.epoch and seq <= ws.seq:
                replay = True
                pending_skips.append(
                    ("replay", f"worker {worker} epoch {epoch} seq "
                               f"{seq} <= {ws.seq}"))
            else:
                replay = False
                new_epoch = epoch != ws.epoch
                if new_epoch:
                    # restart: the next totals are a fresh base, the old
                    # merged history stays — delta-safe by construction
                    ws.counter_last = {}
                    ws.hist_last = {}
                fams = snap.get("families")
                if isinstance(fams, dict):
                    for name, fd in fams.items():
                        try:
                            self._merge_family(ws, str(name), fd)
                        except Exception as e:
                            pending_skips.append(
                                ("family", f"family {name!r} from "
                                           f"{worker}: {e!r}"))
                ws.epoch, ws.seq = epoch, seq
                ws.last_recv = now
                ws.snapshots += 1
                ws.last_ts = _num(snap.get("ts"))
                ws.truncated = int(_num(snap.get("truncated_samples")) or 0)
                for attr in ("health", "state", "prefix_cache", "slo"):
                    val = snap.get(attr)
                    setattr(ws, attr, val if isinstance(val, dict) else None)
        for reason, detail in pending_skips:
            self._skip(reason, detail)
        if replay:
            return False
        self._m_snapshots.inc(worker=worker)
        if ws.last_ts is not None:
            lag = time.time() - ws.last_ts
            if 0 <= lag < 3600:
                self._m_lag.observe(lag)
        return True

    def _merge_family(self, ws: _WorkerView, name: str, fd: Any) -> None:
        if not isinstance(fd, dict):
            return
        kind = fd.get("kind")
        if kind not in ("counter", "gauge", "histogram"):
            return
        label_names = tuple(str(x) for x in (fd.get("label_names") or ()))
        buckets = tuple(float(b) for b in (fd.get("buckets") or ())
                        if _num(b) is not None)
        meta = ws.meta.get(name)
        if (meta is None or meta["kind"] != kind
                or meta["label_names"] != label_names
                or (kind == "histogram" and meta["buckets"] != buckets)):
            # first sight, or re-registered with a different shape:
            # restart this family's books (shape changes can't be added)
            for book in (ws.counters, ws.counter_last, ws.hists,
                         ws.hist_last, ws.gauges):
                book.pop(name, None)
            meta = ws.meta[name] = {"kind": kind,
                                    "help": str(fd.get("help") or ""),
                                    "label_names": label_names,
                                    "buckets": buckets}
        else:
            meta["help"] = str(fd.get("help") or meta["help"])
        samples = fd.get("samples")
        if not isinstance(samples, list):
            return
        if kind == "gauge":
            # snapshots carry full gauge state: replace the family's
            # book wholesale so label-sets that stop appearing (e.g.
            # truncated away) don't stay frozen at their last value
            book: Dict[Tuple[str, ...], float] = {}
            for s in samples:
                if not isinstance(s, dict):
                    continue
                labels = s.get("labels")
                labels = labels if isinstance(labels, dict) else {}
                key = tuple(str(labels.get(k, "")) for k in label_names)
                v = _num(s.get("value"))
                if v is not None:
                    book[key] = v
            if book:
                ws.gauges[name] = book
            else:
                ws.gauges.pop(name, None)
            return
        for s in samples:
            if not isinstance(s, dict):
                continue
            labels = s.get("labels")
            labels = labels if isinstance(labels, dict) else {}
            key = tuple(str(labels.get(k, "")) for k in label_names)
            if kind == "counter":
                v = _num(s.get("value"))
                if v is None or v < 0:
                    continue
                book = ws.counters.setdefault(name, {})
                last = ws.counter_last.setdefault(name, {})
                prev = last.get(key)
                # same-epoch advance merges the delta; an unseen key or
                # an in-epoch regression (shouldn't happen — counters are
                # monotonic) contributes the full total as a fresh base
                delta = v if (prev is None or v < prev) else v - prev
                book[key] = book.get(key, 0.0) + delta
                last[key] = v
            else:  # histogram
                cnt = _num(s.get("count"))
                sm = _num(s.get("sum"))
                counts = s.get("bucket_counts")
                if (cnt is None or sm is None
                        or not isinstance(counts, list)
                        or len(counts) != len(buckets)):
                    continue
                counts = [int(c) for c in counts
                          if _num(c) is not None and c >= 0]
                if len(counts) != len(buckets):
                    continue
                cnt = int(cnt)
                book = ws.hists.setdefault(name, {})
                last = ws.hist_last.setdefault(name, {})
                prev = last.get(key)
                fresh = (prev is None or cnt < prev["count"]
                         or any(c < p for c, p in zip(counts,
                                                      prev["counts"])))
                if fresh:
                    d_sum, d_cnt, d_counts = sm, cnt, counts
                else:
                    d_sum = sm - prev["sum"]
                    d_cnt = cnt - prev["count"]
                    d_counts = [c - p for c, p in zip(counts,
                                                      prev["counts"])]
                cur = book.get(key)
                mn, mx = _num(s.get("min")), _num(s.get("max"))
                if cur is None:
                    book[key] = {"sum": d_sum, "count": d_cnt,
                                 "counts": list(d_counts),
                                 "min": mn, "max": mx}
                else:
                    cur["sum"] += d_sum
                    cur["count"] += d_cnt
                    cur["counts"] = [a + b for a, b in
                                     zip(cur["counts"], d_counts)]
                    if mn is not None:
                        cur["min"] = mn if cur["min"] is None \
                            else min(cur["min"], mn)
                    if mx is not None:
                        cur["max"] = mx if cur["max"] is None \
                            else max(cur["max"], mx)
                last[key] = {"sum": sm, "count": cnt,
                             "counts": list(counts)}

    # --------------------------------------------------------------- reads
    def _is_stale(self, ws: _WorkerView, now: float) -> bool:
        return (now - ws.last_recv) > self.expire_after_s

    def registry(self) -> MetricsRegistry:
        """Rebuild the worker-labeled fleet registry from the merged
        books (fresh object per call: gauge dropping for stale workers
        falls out of the rebuild instead of needing deletion support)."""
        reg = MetricsRegistry()
        now = time.monotonic()
        with self._lock:
            views = sorted(self._workers.values(), key=lambda w: w.worker)
            n_stale = 0
            for ws in views:
                stale = self._is_stale(ws, now)
                n_stale += int(stale)
                for name, meta in ws.meta.items():
                    fleet_label = (self.FLEET_LABEL
                                   if self.FLEET_LABEL
                                   not in meta["label_names"] else "origin")
                    label_names = meta["label_names"] + (fleet_label,)
                    try:
                        if meta["kind"] == "counter":
                            fam = reg.counter(name, meta["help"],
                                              labels=label_names)
                            for key, total in (ws.counters.get(name)
                                               or {}).items():
                                labels = dict(zip(meta["label_names"], key))
                                labels[fleet_label] = ws.worker
                                fam.labels(**labels).inc(total)
                        elif meta["kind"] == "gauge":
                            if stale:
                                continue
                            fam = reg.gauge(name, meta["help"],
                                            labels=label_names)
                            for key, v in (ws.gauges.get(name)
                                           or {}).items():
                                labels = dict(zip(meta["label_names"], key))
                                labels[fleet_label] = ws.worker
                                fam.labels(**labels).set(v)
                        else:
                            if not meta["buckets"]:
                                continue
                            fam = reg.histogram(name, meta["help"],
                                                labels=label_names,
                                                buckets=meta["buckets"])
                            for key, cur in (ws.hists.get(name)
                                             or {}).items():
                                labels = dict(zip(meta["label_names"], key))
                                labels[fleet_label] = ws.worker
                                fam.labels(**labels).restore(
                                    bucket_counts=cur["counts"],
                                    sum=cur["sum"], count=cur["count"],
                                    min=cur["min"], max=cur["max"])
                    except ValueError as e:
                        # cross-worker family shape conflict: first
                        # registration wins, the loser is counted
                        self._skips["export"] = \
                            self._skips.get("export", 0) + 1
                        self._warn(f"export:{name}",
                                   f"family {name!r} from {ws.worker} "
                                   f"conflicts with an already-exported "
                                   f"shape: {e!r}")
            reg.gauge(
                _WORKERS, "Workers currently publishing fresh telemetry "
                "snapshots into the fleet aggregator"
            ).set(float(len(views) - n_stale))
            reg.gauge(
                _STALE, "Workers whose snapshots stopped arriving for "
                "longer than expire_after_s (their gauges are dropped "
                "from the fleet view)"
            ).set(float(n_stale))
            age = reg.gauge(
                _AGE, "Seconds since the last snapshot was received from "
                "the labeled worker", labels=("worker",))
            snaps = reg.counter(_SNAPSHOTS, _H_SNAPSHOTS,
                                labels=("worker",))
            for ws in views:
                age.set(now - ws.last_recv, worker=ws.worker)
                snaps.inc(ws.snapshots, worker=ws.worker)
            skips = reg.counter(_SKIPS, _H_SKIPS, labels=("reason",))
            for reason, n in sorted(self._skips.items()):
                skips.inc(n, reason=reason)
        return reg

    def workers(self) -> List[Dict[str, Any]]:
        """Per-worker table: staleness, merge bookkeeping, the last
        health verdict/SLO summary, and the router-facing prefix-cache
        stats (resident/pinned pages, host-tier bytes, hit rate, tree
        version tag) exactly as the worker published them."""
        now = time.monotonic()
        with self._lock:
            out = []
            for ws in sorted(self._workers.values(),
                             key=lambda w: w.worker):
                out.append({
                    "worker": ws.worker,
                    "stale": self._is_stale(ws, now),
                    "age_s": round(now - ws.last_recv, 3),
                    "epoch": ws.epoch,
                    "seq": ws.seq,
                    "snapshots": ws.snapshots,
                    "truncated_samples": ws.truncated,
                    "healthy": (ws.health or {}).get("healthy"),
                    "failing": (ws.health or {}).get("failing") or [],
                    "slo": ws.slo,
                    "prefix_cache": ws.prefix_cache,
                    "state": ws.state,
                })
        return out

    def fleet_table(self) -> Dict[str, Any]:
        with self._lock:
            skips = dict(self._skips)
        return {"topic": self.topic,
                "expire_after_s": self.expire_after_s,
                "workers": self.workers(),
                "merge_skips": skips}

    def evaluate_health(self, registry: Optional[MetricsRegistry] = None
                        ) -> HealthVerdict:
        """Fleet-scoped verdict over the rebuilt registry: the caller's
        extra rules plus built-in staleness/population/peer-health
        predicates that NAME the offending workers."""
        reg = registry if registry is not None else self.registry()
        now = time.monotonic()
        with self._lock:
            views = list(self._workers.values())
            stale = sorted(w.worker for w in views
                           if self._is_stale(w, now))
            fresh = [w for w in views if not self._is_stale(w, now)]
            unhealthy = sorted(
                w.worker for w in fresh
                if (w.health or {}).get("healthy") is False)
        rules = list(self.rules)

        def _fresh_rule(_):
            return (not stale, len(stale),
                    "stale workers: " + (", ".join(stale) or "none"))

        def _peers_rule(_):
            return (not unhealthy, len(unhealthy),
                    "unhealthy workers: " + (", ".join(unhealthy)
                                             or "none"))

        rules.append(HealthRule("workers_fresh", "predicate",
                                fn=_fresh_rule))
        rules.append(HealthRule("workers_healthy", "predicate",
                                fn=_peers_rule))
        if self.min_workers:
            def _population_rule(_):
                return (len(fresh) >= self.min_workers, len(fresh),
                        f"need >= {self.min_workers} fresh workers")
            rules.append(HealthRule("fleet_population", "predicate",
                                    fn=_population_rule))
        return HealthEvaluator(rules, component="fleet",
                               registry=reg).evaluate()

    # ------------------------------------------------------------ consume
    def start(self) -> "FleetAggregator":
        if self._thread is not None:
            return self
        if self.broker is not None and self._queue is None:
            self._queue = self.broker.subscribe(self.topic)
        self._stop.clear()
        self._thread = threading.Thread(target=self._drain,
                                        name="fleet-aggregator",
                                        daemon=True)
        self._thread.start()
        return self

    def _drain(self) -> None:
        import queue as _queue

        while not self._stop.is_set():
            if self._queue is not None:
                try:
                    msg = self._queue.get(timeout=0.25)
                except _queue.Empty:
                    continue
                self.ingest(msg)
            elif self.url is not None:
                try:
                    import urllib.request

                    url = (f"{self.url}/poll/{self.topic}"
                           f"?sub={self._sub_id}&timeout=1.0")
                    with urllib.request.urlopen(
                            url, timeout=self.timeout) as resp:
                        if resp.status == 204:
                            continue
                        self.ingest(resp.read().decode())
                except Exception as e:
                    self._warn("poll", f"fleet poll failed: {e!r}")
                    if self._stop.wait(0.5):
                        return
            else:
                # nothing to consume from; callers drive ingest() directly
                if self._stop.wait(0.25):
                    return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout + 5.0)
            self._thread = None
        if self.broker is not None and self._queue is not None:
            self.broker.unsubscribe(self.topic, self._queue)
            self._queue = None
        self.stop_server()

    # --------------------------------------------------------- HTTP surface
    def serve(self, port: int = 0) -> int:
        """Fleet endpoints: GET /metrics (worker-labeled Prometheus text
        incl. the mirrored fleet health gauge), GET /fleet (per-worker
        table), GET /health (fleet verdict; 503 when failing)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        agg = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.partition("?")[0]
                try:
                    if path == "/metrics":
                        reg = agg.registry()
                        agg.evaluate_health(registry=reg)
                        self._send(200, reg.to_prometheus().encode(),
                                   "text/plain; version=0.0.4")
                    elif path == "/fleet":
                        self._send(200, json.dumps(
                            agg.fleet_table()).encode())
                    elif path == "/health":
                        verdict = agg.evaluate_health()
                        self._send(200 if verdict.healthy else 503,
                                   json.dumps(verdict.to_dict()).encode())
                    else:
                        self.send_error(404)
                except Exception as e:  # a scrape must not kill the server
                    self._send(500, json.dumps({"error": repr(e)}).encode())

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._http_thread.start()
        return self._httpd.server_address[1]

    def stop_server(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None


# ---------------------------------------------------------------- selftest
def _data_lines(reg: MetricsRegistry) -> str:
    """Prometheus text minus the aggregator's own meta families (their
    age/skip values move with the wall clock; the DATA must not)."""
    return "\n".join(
        ln for ln in reg.to_prometheus().splitlines()
        if "dl4j_fleet_" not in ln)


def schema_roundtrip_selftest(verbose: bool = False) -> int:
    """CI gate: serialize -> merge -> re-export must be bit-stable.

    Proves (1) the wire form is deterministic (sorted keys, two dumps of
    one state identical), (2) re-ingesting the SAME totals under a new
    sequence number changes nothing (no double-count), (3) a publisher
    restart (new epoch, totals reset) adds exactly the new totals on top
    of the merged history (no reset-to-zero artifact), and (4) the
    merged registry re-exports the original values exactly.
    Returns 0 on success, 1 with a message on failure — stdlib only, no
    jax/numpy, callable from scripts/ci_checks.py in a fast subprocess.
    """
    def say(msg):
        if verbose:
            print(f"  {msg}")

    try:
        # throwaway registry with selftest-only families: never exported
        # from a live process, so no docs/observability.md rows
        reg = MetricsRegistry()
        # dl4jlint: disable-next-line=metrics-docs -- selftest-only family
        reg.counter("dl4j_selftest_requests_total", "selftest counter",
                    labels=("status",)).inc(5, status="ok")
        reg.counter("dl4j_selftest_requests_total",
                    labels=("status",)).inc(2, status="error")
        # dl4jlint: disable-next-line=metrics-docs -- selftest-only family
        reg.gauge("dl4j_selftest_depth", "selftest gauge").set(3.25)
        # dl4jlint: disable-next-line=metrics-docs -- selftest-only family
        reg.gauge("dl4j_selftest_nan", "selftest NaN gauge").set(
            float("nan"))
        # dl4jlint: disable-next-line=metrics-docs -- selftest-only family
        hist = reg.histogram("dl4j_selftest_seconds", "selftest histogram",
                             buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.7, 5.0):
            hist.observe(v)
        pub = TelemetryPublisher(
            "w0", registry=reg,
            prefix_cache=lambda: {"version": 7, "resident_pages": 3,
                                  "host_tier_bytes": 4096,
                                  "pinned_pages": 1, "hit_rate": 0.5})
        wire1 = pub.serialize()
        snap = json.loads(wire1)
        assert snap["schema"] == SCHEMA_VERSION, "schema version missing"
        assert snap["prefix_cache"]["version"] == 7, "prefix stats lost"
        nan_sample = snap["families"]["dl4j_selftest_nan"]["samples"][0]
        assert nan_sample["value"] is None, "NaN gauge must map to null"
        redump = json.dumps(json.loads(wire1), sort_keys=True,
                            allow_nan=False)
        assert redump == wire1, "wire form is not round-trip stable"
        say("wire form deterministic")

        agg = FleetAggregator(expire_after_s=3600.0,
                              registry=MetricsRegistry())
        assert agg.ingest(wire1), "first ingest rejected"
        out1 = _data_lines(agg.registry())
        assert 'dl4j_selftest_requests_total{status="ok",worker="w0"} 5' \
            in out1, f"counter not re-exported:\n{out1}"
        assert 'dl4j_selftest_depth{worker="w0"} 3.25' in out1, \
            "gauge not re-exported"
        assert 'dl4j_selftest_seconds_count{worker="w0"} 4' in out1, \
            "histogram count not re-exported"
        assert 'le="+Inf"' in out1, "histogram buckets not re-exported"

        # same totals again (new seq): the merged view must not move
        assert agg.ingest(pub.serialize()), "second ingest rejected"
        out2 = _data_lines(agg.registry())
        assert out2 == out1, ("re-merging unchanged totals changed the "
                              "fleet export (double-count)")
        say("idempotent under unchanged totals")

        # replayed seq: dropped
        assert not agg.ingest(wire1), "stale seq replay was accepted"

        # publisher restart: fresh epoch, totals reset below history
        reg2 = MetricsRegistry()
        reg2.counter("dl4j_selftest_requests_total", "selftest counter",
                     labels=("status",)).inc(3, status="ok")
        pub2 = TelemetryPublisher("w0", registry=reg2)
        assert agg.ingest(pub2.serialize()), "restart ingest rejected"
        out3 = _data_lines(agg.registry())
        assert 'dl4j_selftest_requests_total{status="ok",worker="w0"} 8' \
            in out3, ("epoch-aware merge wrong after restart "
                      f"(want 5+3=8):\n{out3}")
        say("epoch-aware restart merge exact")
        return 0
    except AssertionError as e:
        print(f"fleet schema round-trip selftest FAILED: {e}")
        return 1


if __name__ == "__main__":
    import sys

    sys.exit(schema_roundtrip_selftest(verbose=True))
