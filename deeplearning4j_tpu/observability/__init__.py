"""Unified telemetry core: metrics registry, span tracing, recompile
detection, device-memory gauges, phase timers.

The shared data model the reference never had (its observability is
scattered over ``IterationListener`` hooks, ``PerformanceListener``
sampling and the SBE ``StatsListener`` pipeline): everything in this
framework — fit loops, parallel training masters, the pipeline master,
the inference server, ``ui.stats`` — records into ONE process-wide
``MetricsRegistry``, exportable as JSON or Prometheus text (served live
from ``InferenceServer`` at ``/metrics``).  See docs/observability.md.
"""

from deeplearning4j_tpu.observability.metrics import (
    DEFAULT_BUCKETS, Counter, Gauge, Histogram, MetricFamily,
    MetricsRegistry, get_registry, set_registry,
)
from deeplearning4j_tpu.observability.tracing import (
    Span, SpanTracer, get_tracer, new_trace_id, set_tracer,
)
from deeplearning4j_tpu.observability.profiling import (
    PEAK_FLOPS, StepProfiler, active_profiler, jit_cost_analysis,
    live_buffer_snapshot, model_memory_breakdown, peak_flops_for,
    peak_memory_snapshot,
)
from deeplearning4j_tpu.observability.recompile import (
    RecompileDetector, compile_counter, fingerprint, instrument,
)
from deeplearning4j_tpu.observability.memory import (
    DeviceMemoryMonitor, device_memory_stats, sample_once,
)
from deeplearning4j_tpu.observability.shardstats import (
    LINK_BANDWIDTH, ShardStatsCollector, active_collector,
    attribute_mesh_axes, collective_census, format_ledger, latest_ledgers,
    link_bandwidth_for, program_analysis, record_ledger,
    record_model_ledger, ring_wire_bytes, sharding_ledger,
)
from deeplearning4j_tpu.observability.phases import PhaseTimers
from deeplearning4j_tpu.observability.fleet import (
    FleetAggregator, SLOTracker, TelemetryPublisher,
)
from deeplearning4j_tpu.observability.fitmetrics import (
    FitTelemetry, fit_telemetry,
)
from deeplearning4j_tpu.observability.servingmetrics import ServingMetrics
from deeplearning4j_tpu.observability.health import (
    ClusterStatsAggregator, HealthEvaluator, HealthRule, HealthVerdict,
    StragglerDetector, WorkerTelemetry, default_serving_rules,
    default_training_rules, histogram_quantile,
)
from deeplearning4j_tpu.observability.flightrecorder import (
    FlightEvent, FlightRecorder, StepWatchdog, crash_dump,
    dump_flight_report, get_flight_recorder, get_watchdog,
    read_flight_report, set_flight_recorder, step_guard,
)
from deeplearning4j_tpu.observability.introspection import (
    AnomalyMonitor, IntrospectPlan,
)
from deeplearning4j_tpu.observability.numerics import (
    NumericsMonitor, NumericsPlan, format_precision_ledger, kv_page_ledger,
)

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram", "MetricFamily",
    "MetricsRegistry", "get_registry", "set_registry",
    "Span", "SpanTracer", "get_tracer", "new_trace_id", "set_tracer",
    "PEAK_FLOPS", "StepProfiler", "active_profiler", "jit_cost_analysis",
    "live_buffer_snapshot", "model_memory_breakdown", "peak_flops_for",
    "peak_memory_snapshot",
    "RecompileDetector", "compile_counter", "fingerprint", "instrument",
    "DeviceMemoryMonitor", "device_memory_stats", "sample_once",
    "LINK_BANDWIDTH", "ShardStatsCollector", "active_collector",
    "attribute_mesh_axes", "collective_census", "format_ledger",
    "latest_ledgers", "link_bandwidth_for", "program_analysis",
    "record_ledger", "record_model_ledger", "ring_wire_bytes",
    "sharding_ledger",
    "PhaseTimers", "FleetAggregator", "SLOTracker", "TelemetryPublisher",
    "FitTelemetry", "fit_telemetry", "ServingMetrics",
    "ClusterStatsAggregator", "HealthEvaluator", "HealthRule",
    "HealthVerdict", "StragglerDetector", "WorkerTelemetry",
    "default_serving_rules", "default_training_rules", "histogram_quantile",
    "FlightEvent", "FlightRecorder", "StepWatchdog", "crash_dump",
    "dump_flight_report", "get_flight_recorder", "get_watchdog",
    "read_flight_report", "set_flight_recorder", "step_guard",
    "AnomalyMonitor", "IntrospectPlan",
    "NumericsMonitor", "NumericsPlan", "format_precision_ledger",
    "kv_page_ledger",
]
