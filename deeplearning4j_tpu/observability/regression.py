"""Bench regression sentinel: compare a fresh ``bench_full.json`` against a
committed baseline with per-metric direction + tolerance rules.

Until now the perf trajectory was advisory: ``bench.py`` wrote numbers, a
human eyeballed them.  This module gives it teeth — a rule says which
field of which bench entry matters, which DIRECTION is good, and how much
relative slack the (noisy, CPU-jittered) measurement gets before a change
counts as a regression.  ``scripts/check_bench_regression.py`` wraps it as
a CI gate: exit 0 clean, exit 1 on any regression.

STDLIB ONLY on purpose: the checker script must run in milliseconds with
no jax import, and the module is imported by file path from ``scripts/``
(same pattern as ``check_metrics_docs.py``).

Rule addressing: bench entries live in ``doc["all"]``, each with a
``metric`` name like ``"Decode tokens/sec (d256 L4, b4, ...)"`` — the
part after `` (`` encodes the config and changes across platforms, so
rules match on the PREFIX before it.  ``field`` is a dotted path inside
the entry (``"value"``, ``"variants.gqa2_rolling.tokens_per_sec"``).
With ``scope="doc"`` the rule skips the entry lookup and resolves
``field`` from the DOCUMENT root instead — how the memory sentinels
address ``observability.memory.sentinels.*`` (the ``metric`` string is
then only the display name).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

HIGHER = "higher"   # bigger is better (throughput)
LOWER = "lower"     # smaller is better (latency, step time)


class Rule:
    """One metric's regression policy."""

    __slots__ = ("metric", "field", "direction", "tolerance", "required",
                 "scope")

    def __init__(self, metric: str, field: str = "value",
                 direction: str = HIGHER, tolerance: float = 0.15,
                 required: bool = True, scope: str = "all"):
        if direction not in (HIGHER, LOWER):
            raise ValueError(
                f"direction must be {HIGHER!r} or {LOWER!r}, got {direction!r}")
        if tolerance < 0:
            raise ValueError(f"tolerance must be >= 0, got {tolerance}")
        if scope not in ("all", "doc"):
            raise ValueError(f"scope must be 'all' or 'doc', got {scope!r}")
        self.metric = str(metric)
        self.field = str(field)
        self.direction = direction
        self.tolerance = float(tolerance)
        self.required = bool(required)
        self.scope = scope

    @property
    def key(self) -> str:
        return f"{self.metric} :: {self.field}"

    def to_dict(self) -> Dict[str, Any]:
        return {"metric": self.metric, "field": self.field,
                "direction": self.direction, "tolerance": self.tolerance,
                "required": self.required, "scope": self.scope}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Rule":
        unknown = set(d) - {"metric", "field", "direction", "tolerance",
                            "required", "scope"}
        if unknown:
            raise ValueError(f"unknown rule keys: {sorted(unknown)}")
        if "metric" not in d:
            raise ValueError(f"rule needs a 'metric': {d!r}")
        return Rule(d["metric"], d.get("field", "value"),
                    d.get("direction", HIGHER), d.get("tolerance", 0.15),
                    d.get("required", True), d.get("scope", "all"))


# The committed policy over bench_full.json.  Tolerances are wide (0.4)
# because the CPU bench's run-to-run spread reaches ~25% (bench.py
# SPREAD_THRESHOLD discussion); the sentinel is for collapses, not jitter.
DEFAULT_RULES: List[Rule] = [
    Rule("ResNet-50 images/sec/chip", tolerance=0.4),
    Rule("LeNet-MNIST train step time", direction=LOWER, tolerance=0.4),
    Rule("GravesLSTM char-LM throughput", tolerance=0.4),
    Rule("Transformer char-LM tokens/sec", tolerance=0.4),
    Rule("Decode tokens/sec", tolerance=0.4),
    Rule("Decode tokens/sec", field="variants.gqa2_rolling.tokens_per_sec",
         tolerance=0.4, required=False),
    # continuous-batching generation (bench_generation): the aggregate
    # 16-client decode throughput is the headline the paged-KV engine
    # exists for; the speedup-vs-single-stream ratio guards the batching
    # win itself (an aggregate that only tracks single-stream drift
    # would let the scheduler silently serialize); the exact zero rule
    # pins the decode-side AOT-warmup contract.
    Rule("Generation tokens/sec", tolerance=0.4),
    Rule("Generation tokens/sec", field="speedup_vs_single_stream",
         tolerance=0.4, required=False),
    Rule("Generation tokens/sec", field="p99_ttft_ms", direction=LOWER,
         tolerance=1.0, required=False),
    Rule("Generation tokens/sec", field="steady_state_compiles",
         direction=LOWER, tolerance=0.0, required=False),
    # persistent prefix cache (ISSUE 17): ttft_collapse_ok pins "a hit's
    # p99 TTFT is <= 0.3x a cold miss's" (1 = collapse held; direction=
    # higher + tolerance=0 means any drop to 0 regresses), and
    # hit_rate_nonzero pins "the steady state actually hits the cache" —
    # a change that silently stops matching (version-tag bug, tree never
    # populated) fails immediately rather than showing up as a slow
    # TTFT drift
    Rule("Generation tokens/sec", field="prefix_cache.ttft_collapse_ok",
         tolerance=0.0, required=False),
    Rule("Generation tokens/sec", field="prefix_cache.hit_rate_nonzero",
         tolerance=0.0, required=False),
    Rule("Generation tokens/sec",
         field="prefix_cache.steady_state_compiles",
         direction=LOWER, tolerance=0.0, required=False),
    # decode SLO attribution (ISSUE 18): the ITL histogram must stay
    # populated under the 16-client window, the per-phase breakdown must
    # keep reconciling with the decode loop's busy wall (within 10% —
    # phase_sum_ok pins it), and serializing a federated snapshot must
    # stay host-side only (publisher_host_sync_free: any new device
    # sync drops the sentinel to 0 and fails immediately)
    Rule("Generation tokens/sec", field="slo.itl_populated",
         tolerance=0.0, required=False),
    Rule("Generation tokens/sec", field="slo.phase_sum_ok",
         tolerance=0.0, required=False),
    Rule("Generation tokens/sec", field="slo.publisher_host_sync_free",
         tolerance=0.0, required=False),
    # fused paged decode (ISSUE 19): speedup_vs_gather pins the measured
    # fused-vs-gather-oracle throughput ratio on this container;
    # fused_no_slower (1 = the fused default is at least as fast) and
    # gather_share_collapsed (1 = the per-token decode-step cost the
    # gather used to pay has collapsed) are exact sentinels — a change
    # that silently routes decode back through the materialized gather
    # drops them to 0 and fails immediately; the exact-zero compile rule
    # pins the fused program set's AOT-warmup contract
    Rule("Generation tokens/sec", field="fused_decode.speedup_vs_gather",
         tolerance=0.4, required=False),
    Rule("Generation tokens/sec", field="fused_decode.fused_no_slower",
         tolerance=0.0, required=False),
    Rule("Generation tokens/sec",
         field="fused_decode.gather_share_collapsed",
         tolerance=0.0, required=False),
    Rule("Generation tokens/sec",
         field="fused_decode.steady_state_compiles",
         direction=LOWER, tolerance=0.0, required=False),
    Rule("Long-context train tokens/sec", tolerance=0.4),
    Rule("Serving rows/sec", tolerance=0.4),
    Rule("Serving rows/sec", field="p99_ms", direction=LOWER, tolerance=1.0,
         required=False),
    # zero-compile contract: the baseline is 0, so ANY steady-state
    # compile regresses regardless of tolerance (0 * (1+tol) == 0)
    Rule("Serving rows/sec", field="steady_state_compiles", direction=LOWER,
         tolerance=0.0, required=False),
    Rule("Checkpoint save throughput", tolerance=0.4),
    Rule("Elastic DP samples/sec", tolerance=0.4),
    Rule("Elastic DP samples/sec", field="degraded_vs_lockstep_speedup",
         tolerance=0.5, required=False),
    # stream-to-serving model freshness: seconds from a published event to
    # a swapped-in model serving it, under concurrent load (bench_online).
    # Smaller is better; tolerance is wide because the window includes
    # eval + canary + watch phases whose sleeps jitter on a loaded CPU.
    Rule("Online stream-to-serving freshness", direction=LOWER,
         tolerance=1.0),
    Rule("Online stream-to-serving freshness", field="promoted",
         tolerance=0.0, required=False),
    # stability engine (bench_stability): the guarded train step must not
    # drift slower — the device-side non-finite mask + loss scaling ride
    # inside the XLA program, so a step-time collapse here means the
    # guard fell off the fused path.  Recovery = poison onset -> guard
    # skips -> sentinel verdict -> checkpoint rewind -> training resumed;
    # wide tolerance because the drill includes checkpoint I/O.
    Rule("Stability guarded step", direction=LOWER, tolerance=0.4),
    Rule("Stability guarded step", field="recovery_ms", direction=LOWER,
         tolerance=1.0, required=False),
    # training introspection (bench_introspection): the stats-on fit step
    # must not drift slower — the per-layer reductions are fused into the
    # XLA step and the harvest is one batched transfer per 10th step, so
    # a collapse here means the collection fell off the fused path (or a
    # per-report host-sync storm came back).
    Rule("Introspected train step", direction=LOWER, tolerance=0.4),
    # precision ledger (bench_numerics): the numerics-on fit step must
    # not drift slower (the range stats ride inside the XLA step like
    # the introspection reductions); ledger_overhead_ok pins the <5%
    # overhead contract itself (1 = within budget, direction=higher +
    # tolerance=0 means any drop to 0 regresses), and the exact-zero
    # rule pins "enabling the ledger adds NO steady-state recompiles"
    Rule("Numerics-ledger train step", direction=LOWER, tolerance=0.4),
    Rule("Numerics-ledger train step", field="ledger_overhead_ok",
         tolerance=0.0, required=False),
    Rule("Numerics-ledger train step", field="steady_state_compiles",
         direction=LOWER, tolerance=0.0, required=False),
    # fleet telemetry plane (bench_fleet, ISSUE 18): publish->ingest lag
    # across the two-process federation must stay bounded (lower; wide
    # tolerance — the HTTP long-poll handoff jitters on a loaded CPU),
    # publisher_overhead_ok pins the <2%-on-the-train-step contract, and
    # the kill/restart drill's verdicts must stay 1: the dead worker is
    # detected AND named, and the restarted epoch merges with no
    # double-count and no reset-to-zero
    Rule("Fleet telemetry ingest lag", direction=LOWER, tolerance=3.0),
    Rule("Fleet telemetry ingest lag", field="publisher_overhead_ok",
         tolerance=0.0, required=False),
    Rule("Fleet telemetry ingest lag", field="federation.stale_detected",
         tolerance=0.0, required=False),
    Rule("Fleet telemetry ingest lag",
         field="federation.stale_worker_named",
         tolerance=0.0, required=False),
    Rule("Fleet telemetry ingest lag",
         field="federation.restart_merge_ok",
         tolerance=0.0, required=False),
    # serving fleet (bench_fleet_serving, ISSUE 20): the 4-replica
    # aggregate is the headline; scaling_4x_ok pins the >=3.0x floor of
    # the 4-vs-1 aggregate (1 = floor held; direction=higher +
    # tolerance=0 means any drop to 0 regresses) with the raw speedup
    # tracked alongside; affinity_beats_random pins "cache-aware
    # placement finds more resident prefixes than the seeded-random
    # control"; zero_queued_errors pins the failover contract (a
    # SIGKILLed replica's queued requests land on survivors with no
    # client-visible error) and rejoin/rollback verdicts pin the
    # lifecycle halves; the exact-zero compile rule pins steady-state
    # traffic across the scaling+affinity arms (captured before the
    # kill drill — a restart legitimately re-runs its AOT warmup)
    Rule("Fleet serving tokens/sec", tolerance=0.4),
    Rule("Fleet serving tokens/sec", field="scaling.speedup_4x_vs_1",
         tolerance=0.4, required=False),
    Rule("Fleet serving tokens/sec", field="scaling.scaling_4x_ok",
         tolerance=0.0, required=False),
    Rule("Fleet serving tokens/sec", field="p99_ttft_ms",
         direction=LOWER, tolerance=1.0, required=False),
    Rule("Fleet serving tokens/sec",
         field="affinity.affinity_beats_random",
         tolerance=0.0, required=False),
    Rule("Fleet serving tokens/sec",
         field="failover.zero_queued_errors",
         tolerance=0.0, required=False),
    Rule("Fleet serving tokens/sec", field="failover.recovery_ms",
         direction=LOWER, tolerance=3.0, required=False),
    Rule("Fleet serving tokens/sec", field="failover.restart_rejoined",
         tolerance=0.0, required=False),
    Rule("Fleet serving tokens/sec", field="rollout.promoted",
         tolerance=0.0, required=False),
    Rule("Fleet serving tokens/sec", field="rollout.rolled_back_all",
         tolerance=0.0, required=False),
    Rule("Fleet serving tokens/sec", field="steady_state_compiles",
         direction=LOWER, tolerance=0.0, required=False),
    # memory & collective-communication sentinels (bench _memory_measure
    # -> observability.memory.sentinels): FLIPPED to the ZeRO baselines
    # by the update-sharding PR (ROADMAP item 2, arXiv 2004.13336) — the
    # sentinels now pin the SHARDED numbers: updater-state replication
    # ~1 (was K), params ~1, the window's collective/wire bytes in the
    # all-to-all + all-gather decomposition (at or below the old
    # all-reduce wire bytes), and per-device train-state bytes at the
    # sharded level.  direction=lower + tolerance=0 means "any increase
    # regresses" — a change that silently knocks the wrapper back to
    # replicated updater state fails the replication rule immediately.
    # Optional because the section needs the virtual mesh (subprocess,
    # like the elastic bench).
    Rule("Memory: updater replication (4-replica DP, ZeRO)", scope="doc",
         field="observability.memory.sentinels.updater_replication_factor",
         direction=LOWER, tolerance=0.0, required=False),
    Rule("Memory: param replication (4-replica DP, ZeRO)", scope="doc",
         field="observability.memory.sentinels.param_replication_factor",
         direction=LOWER, tolerance=0.0, required=False),
    Rule("Memory: collective bytes/step (4-replica DP, ZeRO)", scope="doc",
         field="observability.memory.sentinels.collective_bytes_per_step",
         direction=LOWER, tolerance=0.25, required=False),
    Rule("Memory: wire bytes/step (4-replica DP, ZeRO)", scope="doc",
         field="observability.memory.sentinels.wire_bytes_per_step",
         direction=LOWER, tolerance=0.25, required=False),
    Rule("Memory: per-device train bytes (4-replica DP, ZeRO)", scope="doc",
         field="observability.memory.sentinels.per_device_bytes",
         direction=LOWER, tolerance=0.25, required=False),
    # the ZeRO window's zero-steady-state-recompile contract: the
    # baseline is EXACTLY 0, so any steady-state compile of the sharded
    # window regresses regardless of tolerance (0 * (1+tol) == 0)
    Rule("Memory: ZeRO window steady-state recompiles", scope="doc",
         field=("observability.memory.sentinels"
                ".zero_steady_state_recompiles"),
         direction=LOWER, tolerance=0.0, required=False),
    # bench_zero: ZeRO step time must stay in the replicated band (the
    # sharded update + gather must not fall off the fused path), and the
    # per-device-bytes ratio guards the memory win itself (~(2+K)/(3K)
    # for adam; a ratio drifting toward 1 means the sharding fell off)
    Rule("ZeRO DP step time", direction=LOWER, tolerance=0.4),
    Rule("ZeRO DP step time", field="per_device_bytes_ratio",
         direction=LOWER, tolerance=0.1, required=False),
]


# The committed policy over kernel_trust.json (observability.kerneldiff
# sweeps; ``python -m ...kerneldiff --baseline kernel_trust.json``).
# Worst-config max-rel-error per kernel: direction=lower with a 1.0
# tolerance — the CPU-interpret sweep is deterministic, so the slack is
# for dtype-budget headroom, not jitter; a doubling of any kernel's
# divergence regresses.  The doc-scope rule pins "no config anywhere
# fails its budget" exactly (baseline 0, tolerance 0).
KERNEL_TRUST_RULES: List[Rule] = [
    Rule("Kernel max rel error (flash_attention)", direction=LOWER,
         tolerance=1.0),
    Rule("Kernel max rel error (dot_product_attention)", direction=LOWER,
         tolerance=1.0),
    Rule("Kernel max rel error (gather_pages)", direction=LOWER,
         tolerance=0.0),
    Rule("Kernel max rel error (paged_attention)", direction=LOWER,
         tolerance=1.0),
    # the fused decode kernel (ISSUE 19) sweeps BOTH impls behind the
    # seam (lax fallback + interpreted Pallas) in one flat comparison;
    # the train-step epilogue likewise covers residual/prologue/norm-only
    # variants under one entry
    Rule("Kernel max rel error (fused_paged_attention)", direction=LOWER,
         tolerance=1.0),
    Rule("Kernel max rel error (fused_dropout_residual_norm)",
         direction=LOWER, tolerance=1.0),
    Rule("Kernel max rel error (pallas_lrn)", direction=LOWER,
         tolerance=1.0, required=False),
    Rule("Kernel max rel error (pallas_bn_inference)", direction=LOWER,
         tolerance=1.0, required=False),
    Rule("Kernel max rel error (pallas_bn_training)", direction=LOWER,
         tolerance=1.0, required=False),
    Rule("Kernel trust failing configs", scope="doc",
         field="summary.failing_configs", direction=LOWER, tolerance=0.0),
]


def load_rules(path: str) -> List[Rule]:
    """Rules from a JSON file: a list of rule dicts (see Rule.from_dict)."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, list):
        raise ValueError(f"{path}: rules file must be a JSON list")
    return [Rule.from_dict(d) for d in data]


# ------------------------------------------------------------- extraction
def _find_entry(doc: Dict[str, Any], metric_prefix: str) -> Optional[Dict]:
    for entry in doc.get("all", []) or []:
        if str(entry.get("metric", "")).startswith(metric_prefix):
            return entry
    return None


def _get_field(entry: Dict[str, Any], dotted: str) -> Optional[float]:
    cur: Any = entry
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def extract(doc: Dict[str, Any], rule: Rule) -> Optional[float]:
    if rule.scope == "doc":
        return _get_field(doc, rule.field)
    entry = _find_entry(doc, rule.metric)
    if entry is None:
        return None
    return _get_field(entry, rule.field)


# -------------------------------------------------------------- comparison
class Verdict:
    """One rule's outcome: ``status`` in {"ok", "improved", "regressed",
    "missing", "no_baseline"}."""

    __slots__ = ("rule", "status", "baseline", "fresh", "limit", "detail")

    def __init__(self, rule: Rule, status: str, baseline, fresh, limit,
                 detail: str):
        self.rule = rule
        self.status = status
        self.baseline = baseline
        self.fresh = fresh
        self.limit = limit
        self.detail = detail

    def to_dict(self) -> Dict[str, Any]:
        return {"metric": self.rule.metric, "field": self.rule.field,
                "direction": self.rule.direction,
                "tolerance": self.rule.tolerance, "status": self.status,
                "baseline": self.baseline, "fresh": self.fresh,
                "limit": self.limit, "detail": self.detail}


class Report:
    def __init__(self, verdicts: List[Verdict]):
        self.verdicts = verdicts

    @property
    def regressions(self) -> List[Verdict]:
        return [v for v in self.verdicts if v.status == "regressed"]

    @property
    def exit_code(self) -> int:
        return 1 if self.regressions else 0

    def to_dict(self) -> Dict[str, Any]:
        return {"regressed": len(self.regressions),
                "checked": len(self.verdicts),
                "verdicts": [v.to_dict() for v in self.verdicts]}

    def format(self) -> str:
        lines = []
        for v in self.verdicts:
            mark = {"ok": "ok       ", "improved": "improved ",
                    "regressed": "REGRESSED", "missing": "missing  ",
                    "no_baseline": "skipped  "}[v.status]
            lines.append(f"{mark} {v.rule.key}: {v.detail}")
        n = len(self.regressions)
        lines.append(f"{'FAIL' if n else 'PASS'}: {n} regression(s) in "
                     f"{len(self.verdicts)} checked rule(s)")
        return "\n".join(lines)


def compare(baseline: Dict[str, Any], fresh: Dict[str, Any],
            rules: Optional[List[Rule]] = None) -> Report:
    """Evaluate every rule: a fresh value past ``baseline * (1 ± tol)``
    in the BAD direction regresses; a missing fresh value regresses when
    the rule is ``required``; a MISSING baseline skips the rule
    (``no_baseline`` — there is nothing to hold the line against).  A
    zero baseline is enforced, not skipped: with ``direction=lower`` and
    ``tolerance=0`` it means "any increase regresses" — the
    steady-state-compiles contract depends on exactly that."""
    verdicts: List[Verdict] = []
    for rule in (rules if rules is not None else DEFAULT_RULES):
        base = extract(baseline, rule)
        new = extract(fresh, rule)
        if base is None:
            verdicts.append(Verdict(rule, "no_baseline", None, new, None,
                                    "no baseline value"))
            continue
        if new is None:
            status = "regressed" if rule.required else "missing"
            verdicts.append(Verdict(
                rule, status, base, None, None,
                "value missing from fresh run"
                + ("" if rule.required else " (optional)")))
            continue
        if rule.direction == HIGHER:
            limit = base * (1.0 - rule.tolerance)
            regressed = new < limit
            improved = new > base
        else:
            limit = base * (1.0 + rule.tolerance)
            regressed = new > limit
            improved = new < base
        status = ("regressed" if regressed
                  else "improved" if improved else "ok")
        arrow = "<" if rule.direction == HIGHER else ">"
        detail = (f"fresh {new:g} vs baseline {base:g} "
                  f"(fails when {arrow} {limit:g})")
        verdicts.append(Verdict(rule, status, base, new, limit, detail))
    return Report(verdicts)


def check_files(baseline_path: str, fresh_path: str,
                rules: Optional[List[Rule]] = None) -> Report:
    with open(baseline_path) as f:
        baseline = json.load(f)
    with open(fresh_path) as f:
        fresh = json.load(f)
    return compare(baseline, fresh, rules)
