"""Training introspection: device-side per-layer gradient/update/
activation statistics, harvested once per reporting interval.

The reference system's headline observability feature was the web
training UI fed by ``StatsListener``/``StatsStorage`` (deeplearning4j-ui):
per-layer weight, gradient, update and activation distributions — the
diagnostics practitioners use to catch vanishing/exploding gradients,
dead units, and mistuned learning rates *before* a run is wasted.  The
PR-11 stability engine only reacts once values go non-finite; gradual
degradation was invisible.  This module is the "see inside the model"
tier, rebuilt for the one-XLA-program world:

- **device-side collection** (jit-safe half, used INSIDE every train
  step): per-layer gradient norm, update norm (computed from the
  ``params - new_params`` delta, so it reflects exactly what the updater
  + stability guard applied), param norm, and — via the facades' loss
  functions — activation mean/std/fraction-zero.  One fused reduction
  pass per leaf; the results live in a reserved ``__introspect__``
  subtree of the updater-state pytree (the ``__stability__`` pattern),
  so they stack per replica in ``ParallelWrapper``, replicate in
  ``SyncTrainingMaster``, donate with the step, and checkpoint with the
  Adam moments.  Zero host syncs on non-report steps, zero recompiles
  after the first step;
- **harvest** (host half): ``StatsListener`` pulls the subtree with ONE
  batched device->host transfer per reporting interval and fans it out
  into extended ``StatsReport`` fields (per-replica when the state is
  stacked ``[K, L]``), the ``dl4j_layer_*`` metric families, and the
  ``AnomalyMonitor``;
- **anomaly rules**: ``AnomalyMonitor`` checks each harvested report
  against the update:param-ratio band, the dead-unit fraction cap, and
  the cross-layer gradient-norm spread, emitting ONE rate-limited
  warning + ``introspection_anomaly`` flight event naming the offending
  layer.  The same thresholds are queryable as ``HealthRule`` kinds
  (``update_ratio_band`` / ``max_dead_fraction`` /
  ``max_gradient_norm_ratio``) against the published gauges, so
  ``GET /health`` sees them too.

Metric families (docs/observability.md): ``dl4j_layer_gradient_norm``,
``dl4j_layer_update_norm``, ``dl4j_layer_update_ratio``,
``dl4j_layer_dead_fraction``.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Reserved subtree of the updater-state pytree.  Living inside updater
# state means the per-layer stat vectors are stacked per replica by
# ParallelWrapper, replicated by the sync master, donated with the step,
# and checkpointed/restored by CheckpointManager without extra plumbing.
STATE_KEY = "__introspect__"

_GRAD = "dl4j_layer_gradient_norm"
_UPD = "dl4j_layer_update_norm"
_RATIO = "dl4j_layer_update_ratio"
_DEAD = "dl4j_layer_dead_fraction"

logger = logging.getLogger("deeplearning4j_tpu.observability")


# ---------------------------------------------------------------------------
# plan: the per-net layer inventory both halves agree on
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class IntrospectPlan:
    """Ordered layer-name inventory for one net: ``grad_names`` index the
    ``[L]`` gradient/update/param-norm vectors, ``act_names`` the ``[A]``
    activation-summary vectors (empty when activation collection is
    off).  Built identically at trace time (step cores) and harvest time
    (StatsListener), so vector slot k always means the same layer."""

    grad_names: Tuple[str, ...]
    act_names: Tuple[str, ...]
    policy: Any

    @property
    def collect_acts(self) -> bool:
        return bool(self.act_names)


def plan_for(net) -> Optional[IntrospectPlan]:
    """The net's IntrospectPlan, or None when ``conf.introspection`` is
    unset.  Works for both facades (ComputationGraph is detected by its
    ``conf.nodes``)."""
    policy = getattr(net.conf, "introspection", None)
    if policy is None:
        return None
    nodes = getattr(net.conf, "nodes", None)
    if nodes is not None:  # ComputationGraph
        grad = tuple(n.name for n in nodes
                     if n.layer is not None and n.layer.has_params())
        acts = tuple(n.name for n in nodes if n.layer is not None)
    else:                  # MultiLayerNetwork
        grad = tuple(l.name for l in net.layers if l.has_params())
        acts = tuple(l.name for l in net.layers)
    if not policy.collect_activations:
        acts = ()
    return IntrospectPlan(grad_names=grad, act_names=acts, policy=policy)


# ---------------------------------------------------------------------------
# jit-safe half: called INSIDE the train steps (no host syncs anywhere)
# ---------------------------------------------------------------------------

def _layout(plan: IntrospectPlan) -> Dict[str, slice]:
    """Slice layout of the packed state vector.  ONE flat ``[N]`` array
    (not a dict of seven) keeps the per-step dispatch overhead at a
    single extra buffer in/out of the jitted call — measurably cheaper
    on dispatch-bound small models (PROFILE.md's ~1 ms floor)."""
    L, A = len(plan.grad_names), len(plan.act_names)
    off = {"iteration": slice(0, 1),
           "grad_norm": slice(1, 1 + L),
           "update_norm": slice(1 + L, 1 + 2 * L),
           "param_norm": slice(1 + 2 * L, 1 + 3 * L)}
    base = 1 + 3 * L
    if A:
        off["act_mean"] = slice(base, base + A)
        off["act_std"] = slice(base + A, base + 2 * A)
        off["act_zero"] = slice(base + 2 * A, base + 3 * A)
    off["__size__"] = slice(0, base + 3 * A)
    return off


def initial_state(plan: IntrospectPlan) -> Dict[str, jax.Array]:
    """Fresh device-side introspection state (the facades add it to
    ``updater_state`` at ``init()``; ``iteration`` -1 marks 'no step
    collected yet')."""
    n = _layout(plan)["__size__"].stop
    v = jnp.zeros((n,), jnp.float32).at[0].set(-1.0)
    return {"packed": v}


def ensure_state(net) -> None:
    """Make sure an introspection-enabled net carries the state subtree
    (nets initialized before the policy was set, deserialized nets)."""
    plan = plan_for(net)
    if plan is not None and STATE_KEY not in net.updater_state:
        net.updater_state[STATE_KEY] = initial_state(plan)


def split_state(upd_state):
    """(introspection subtree or None, remaining updater state) —
    trace-time split; the remainder is what ``updaters.update`` (and the
    stability engine's own split) understand."""
    if STATE_KEY not in upd_state:
        return None, upd_state
    return (upd_state[STATE_KEY],
            {k: v for k, v in upd_state.items() if k != STATE_KEY})


def unpack_aux(plan, aux):
    """Normalize a loss function's aux to ``(new_net_state, new_carries,
    act_stats)``: with activation collection the facades' loss aux grows
    a third slot (trace-time shape, fixed per plan).  One shared helper
    so the four step builders (both facades, both masters) cannot
    silently diverge on the aux convention."""
    if plan is not None and plan.collect_acts:
        return aux
    new_state, carries = aux
    return new_state, carries, None


def attach(new_upd_state, plan, *, grads, params, new_params, iteration,
           act_stats=None, grad_scale=None):
    """Insert the refreshed ``__introspect__`` subtree into a step's new
    updater state (no-op when introspection is off) — the single wiring
    point the step cores share; see ``collect`` for the semantics of
    each argument."""
    if plan is not None:
        new_upd_state[STATE_KEY] = collect(
            plan, grads=grads, params=params, new_params=new_params,
            iteration=iteration, act_stats=act_stats,
            grad_scale=grad_scale)
    return new_upd_state


def _sq_sum(tree) -> jax.Array:
    """Σ x² over every leaf of a subtree, accumulated in f32 — one
    reduction per leaf, fused by XLA into the pass that already reads
    the gradients/params."""
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(tree):
        total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return total


def act_summary(named_acts: Sequence[Tuple[str, jax.Array]],
                dead_eps: float = 0.0) -> Dict[str, jax.Array]:
    """Per-layer activation summaries, stacked in input order: mean,
    std, and fraction-"dead" (``|a| <= dead_eps``; exact zeros for the
    ReLU dying-unit case).  Called inside the facades' loss functions
    while the activations are still live in the graph."""
    means, stds, zeros = [], [], []
    for _, a in named_acts:
        a = jnp.asarray(a).astype(jnp.float32)
        n = a.size
        # moment form: sum, sum-of-squares and zero-count are sibling
        # reductions over ONE read of the activation tensor (XLA
        # multi-output fusion) — jnp.std's mean-then-deviations shape
        # would cost a second full pass per layer
        s1 = jnp.sum(a)
        s2 = jnp.sum(jnp.square(a))
        z = jnp.sum((jnp.abs(a) <= dead_eps).astype(jnp.float32))
        mean = s1 / n
        var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
        means.append(mean)
        stds.append(jnp.sqrt(var))
        zeros.append(z / n)
    return {"act_mean": jnp.stack(means), "act_std": jnp.stack(stds),
            "act_zero": jnp.stack(zeros)}


def collect(plan: IntrospectPlan, *, grads, params, new_params, iteration,
            act_stats=None, grad_scale=None) -> Dict[str, jax.Array]:
    """One step's refreshed introspection state: per-layer gradient norm
    (``grad_scale`` unscales loss-scaled gradients — norms are
    positively homogeneous, so scaling after the sqrt is exact), update
    norm from the ``params - new_params`` delta (reflects exactly what
    was applied, including LR overrides, stability masks and backoffs),
    and the pre-update param norm the update:param ratio divides by."""
    gn, un, pn = [], [], []
    for name in plan.grad_names:
        gn.append(jnp.sqrt(_sq_sum(grads.get(name, {}))))
        pn.append(jnp.sqrt(_sq_sum(params[name])))
        un.append(jnp.sqrt(_sq_sum(jax.tree_util.tree_map(
            lambda o, n: o.astype(jnp.float32) - n.astype(jnp.float32),
            params[name], new_params[name]))))
    grad_norm = jnp.stack(gn)
    if grad_scale is not None:
        grad_norm = grad_norm * grad_scale
    parts = [jnp.asarray(iteration, jnp.float32).reshape((1,)),
             grad_norm, jnp.stack(un), jnp.stack(pn)]
    if plan.act_names:
        if act_stats is None:
            raise ValueError(
                "plan collects activations but no act_stats were passed")
        parts += [act_stats["act_mean"], act_stats["act_std"],
                  act_stats["act_zero"]]
    return {"packed": jnp.concatenate(parts)}


# ---------------------------------------------------------------------------
# host half: harvest, metrics, anomaly rules
# ---------------------------------------------------------------------------

def latest(model):
    """The most recent device-side introspection state for this model:
    the masters stamp ``_introspect_live`` per step/window (their live
    state never touches ``model.updater_state`` mid-fit; the wrapper's
    stamp is the stacked ``[K, L]`` per-replica view), the facades'
    ``updater_state`` is always current."""
    live = getattr(model, "_introspect_live", None)
    if live is not None:
        return live
    return model.updater_state.get(STATE_KEY)


def harvest(state, plan: IntrospectPlan) -> Optional[Dict[str, Any]]:
    """Fan a device-side state out into per-layer host dicts with ONE
    batched device->host transfer.  A stacked ``[K, L]`` state (the
    wrapper's per-replica view) yields ``per_replica`` lists next to the
    healthy-mean scalars."""
    if state is None or plan is None:
        return None
    packed = np.asarray(jax.device_get(state["packed"]))
    lay = _layout(plan)
    if packed.shape[-1] != lay["__size__"].stop:
        return None   # state from a different plan shape (stale stamp)
    stacked = packed.ndim == 2
    replicas = int(packed.shape[0]) if stacked else None
    host = {k: (packed[:, sl] if stacked else packed[sl])
            for k, sl in lay.items() if k != "__size__"}
    host["iteration"] = host["iteration"][..., 0]

    def split(vec, i):
        col = vec[:, i] if stacked else None
        val = float(vec[i]) if not stacked else _finite_mean(col)
        return val, col

    def entry(vec, i, key):
        val, col = split(vec, i)
        out = {key: val}
        if col is not None:
            out["per_replica"] = [float(v) for v in col]
        return out

    gradient_stats, update_stats = {}, {}
    for i, name in enumerate(plan.grad_names):
        gradient_stats[name] = entry(host["grad_norm"], i, "norm")
        e = entry(host["update_norm"], i, "norm")
        p, _ = split(host["param_norm"], i)
        e["param_norm"] = p
        e["ratio"] = (e["norm"] / p if p and math.isfinite(p) and p > 0
                      else float("nan"))
        update_stats[name] = e
    activation_stats = {}
    for i, name in enumerate(plan.act_names):
        activation_stats[name] = {
            "mean": split(host["act_mean"], i)[0],
            "std": split(host["act_std"], i)[0],
            "zero_fraction": split(host["act_zero"], i)[0],
        }
        if stacked:
            activation_stats[name]["per_replica_zero_fraction"] = [
                float(v) for v in host["act_zero"][:, i]]
    it = host["iteration"]
    return {
        "iteration": int(it.max()) if stacked else int(it),
        "replicas": replicas,
        "gradient_stats": gradient_stats,
        "update_stats": update_stats,
        "activation_stats": activation_stats,
    }


def _finite_mean(col) -> float:
    vals = col[np.isfinite(col)]
    return float(vals.mean()) if vals.size else float("nan")


def harvest_model(model) -> Optional[Dict[str, Any]]:
    """``harvest(latest(model), plan_for(model))`` — the StatsListener
    entry point; None when introspection is off or nothing collected."""
    plan = plan_for(model)
    if plan is None:
        return None
    h = harvest(latest(model), plan)
    if h is not None and h["iteration"] < 0:
        return None   # state allocated but no step collected yet
    return h


def publish_metrics(harvested: Dict[str, Any], registry=None) -> None:
    """Mirror a harvested report into the ``dl4j_layer_*`` gauge
    families (healthy-mean values; the per-replica detail stays in the
    StatsReport).  The health-rule kinds ``update_ratio_band`` /
    ``max_dead_fraction`` / ``max_gradient_norm_ratio`` read these."""
    if registry is None:
        from deeplearning4j_tpu.observability import get_registry
        registry = get_registry()
    g_grad = registry.gauge(
        _GRAD, "Per-layer L2 gradient norm of the most recent introspected "
        "train step (device-computed; unscaled when loss scaling is on)",
        labels=("layer",))
    g_upd = registry.gauge(
        _UPD, "Per-layer L2 norm of the parameter update actually applied "
        "by the most recent introspected train step", labels=("layer",))
    g_ratio = registry.gauge(
        _RATIO, "Per-layer update:param norm ratio of the most recent "
        "introspected step (~1e-3 is the classic healthy band; read by "
        "the update_ratio_band health rule)", labels=("layer",))
    g_dead = registry.gauge(
        _DEAD, "Per-layer fraction of activations at (or within dead_eps "
        "of) zero in the most recent introspected step — dead-unit "
        "detection; read by the max_dead_fraction health rule",
        labels=("layer",))
    for layer, e in harvested["gradient_stats"].items():
        if math.isfinite(e["norm"]):
            g_grad.set(e["norm"], layer=layer)
    for layer, e in harvested["update_stats"].items():
        if math.isfinite(e["norm"]):
            g_upd.set(e["norm"], layer=layer)
        if math.isfinite(e["ratio"]):
            g_ratio.set(e["ratio"], layer=layer)
    for layer, e in harvested["activation_stats"].items():
        if math.isfinite(e["zero_fraction"]):
            g_dead.set(e["zero_fraction"], layer=layer)


class AnomalyMonitor:
    """Per-report anomaly rules over harvested introspection stats.

    Three checks, mirroring the ``HealthRule`` kinds so the live warning
    and the ``/health`` verdict agree:

    - ``update_ratio_band`` — a layer's update:param ratio outside
      ``[band_low, band_high]`` (too low: the layer is frozen /
      vanishing; too high: the LR is about to bounce the weights);
    - ``max_dead_fraction`` — a layer's activation zero-fraction above
      the cap (dying-ReLU detection);
    - ``max_gradient_norm_ratio`` — the max:min spread of per-layer
      gradient norms above the cap (vanishing/exploding across depth).

    Each violation emits ONE rate-limited warning + an
    ``introspection_anomaly`` flight event naming the offending layer;
    ``check`` returns every violation for programmatic use."""

    def __init__(self, component: str = "training",
                 band_low: float = 1e-7, band_high: float = 1.0,
                 max_dead_fraction: float = 0.95,
                 max_gradient_norm_ratio: float = 1e6,
                 min_iteration: int = 1, warn_interval_s: float = 30.0,
                 warn=None):
        if band_low > band_high:
            raise ValueError(f"band_low {band_low} > band_high {band_high}")
        self.component = component
        self.band_low = float(band_low)
        self.band_high = float(band_high)
        self.max_dead_fraction = float(max_dead_fraction)
        self.max_gradient_norm_ratio = float(max_gradient_norm_ratio)
        # the very first updates out of a fresh init are legitimately
        # out-of-band (zero Adam moments, warmup); give them grace
        self.min_iteration = int(min_iteration)
        self.warn_interval_s = float(warn_interval_s)
        self.warn = warn or logger.warning
        self._lock = threading.Lock()
        self._last_warn: Dict[Tuple[str, str], float] = {}

    def check(self, harvested: Dict[str, Any],
              iteration: Optional[int] = None) -> List[Dict[str, Any]]:
        if harvested is None:
            return []
        it = harvested.get("iteration", iteration) or 0
        if it < self.min_iteration:
            return []
        violations: List[Dict[str, Any]] = []
        for layer, e in harvested["update_stats"].items():
            r = e.get("ratio")
            if r is None or not math.isfinite(r) or r == 0.0:
                continue   # skipped/no-op step: no evidence either way
            if not (self.band_low <= r <= self.band_high):
                violations.append({
                    "rule": "update_ratio_band", "layer": layer,
                    "value": r,
                    "limit": (self.band_low, self.band_high)})
        for layer, e in harvested["activation_stats"].items():
            z = e.get("zero_fraction")
            if z is not None and math.isfinite(z) \
                    and z > self.max_dead_fraction:
                violations.append({
                    "rule": "max_dead_fraction", "layer": layer,
                    "value": z, "limit": self.max_dead_fraction})
        norms = {l: e["norm"] for l, e in harvested["gradient_stats"].items()
                 if math.isfinite(e["norm"]) and e["norm"] > 0}
        if len(norms) >= 2:
            lo_l = min(norms, key=norms.get)
            hi_l = max(norms, key=norms.get)
            ratio = norms[hi_l] / norms[lo_l]
            if ratio > self.max_gradient_norm_ratio:
                violations.append({
                    "rule": "max_gradient_norm_ratio", "layer": lo_l,
                    "value": ratio, "limit": self.max_gradient_norm_ratio,
                    "detail": f"max {hi_l}={norms[hi_l]:.3g} vs "
                              f"min {lo_l}={norms[lo_l]:.3g}"})
        for v in violations:
            self._emit(v, it)
        return violations

    def _emit(self, v: Dict[str, Any], iteration: int) -> None:
        key = (v["rule"], v["layer"])
        now = time.monotonic()
        with self._lock:
            if now - self._last_warn.get(key, -math.inf) \
                    < self.warn_interval_s:
                return
            self._last_warn[key] = now
        from deeplearning4j_tpu.observability import get_flight_recorder
        get_flight_recorder().record(
            "introspection_anomaly", component=self.component,
            rule=v["rule"], layer=v["layer"], value=float(v["value"]),
            iteration=int(iteration))
        self.warn(
            f"introspection anomaly in {self.component}: {v['rule']} on "
            f"layer '{v['layer']}' (value {v['value']:.4g}, limit "
            f"{v['limit']}{', ' + v['detail'] if 'detail' in v else ''}) "
            f"at iteration {iteration}")
