"""Kernel-trust differential harness.

The repo's fused kernels (Pallas flash attention, the fused LRN/BN
passes, the paged-attention decode path) were validated by their unit
tests — which is trust by sampling.  This module is trust by SWEEP: run
every fused kernel against an independent float64 numpy reference over
a shape × dtype × masking grid, record per-config max-abs / max-rel
error and the ULP distribution in the output dtype, classify every
divergence, and write the whole thing to a machine-readable
``kernel_trust.json`` the regression sentinel can hold the line on
(``regression.KERNEL_TRUST_RULES``).

Divergence classes (docs/observability.md "Numerics" has the triage
runbook):

- ``within_tolerance`` — every config's max rel error is inside the
  dtype's budget; the kernel is trusted;
- ``tolerance_only`` — some configs exceed the budget but stay within a
  small multiple of it: an accumulation-order artifact, loosen the
  budget or tighten the kernel, but nothing is wrong;
- ``shape_dependent`` — the SAME dtype passes on some shapes and fails
  on others: a tiling/padding/masking seam, treat as a bug until
  explained;
- ``kernel_divergence`` — every config of a dtype is out of budget: the
  kernel computes something different from the reference;
- ``reference_setup`` — the config did not produce numbers at all
  because the HARNESS environment broke (jax API drift, missing
  platform); the kernel itself is unjudged.  The 2025 incident where
  18/37 flash-attention tests failed on jax 0.4.37 (``jax.typeof``,
  ``pltpu.CompilerParams``, ``jax.shard_map`` — all import/attribute
  drift, zero numerics involved) is the canonical example, recorded in
  ``FLASH_TEST_TRIAGE`` and embedded in every report.

Metric family: ``dl4j_kernel_max_rel_error{kernel}``.

CLI::

    JAX_PLATFORMS=cpu python -m deeplearning4j_tpu.observability.kerneldiff \
        --out kernel_trust.json [--full] [--baseline kernel_trust.json]

``--baseline`` re-runs the sweep and fails (exit 1) if any kernel's
worst-config error regressed past the sentinel rules.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_KERNEL_ERR = "dl4j_kernel_max_rel_error"

# Per-dtype max-rel-error budgets vs the float64 reference.  float32
# budgets absorb accumulation-order differences (blockwise online
# softmax vs one-shot); bfloat16 budgets absorb the 8-bit mantissa.
# A config within TOLERANCE_SLACK × budget is "tolerance_only", beyond
# that it is a divergence.
DTYPE_BUDGET = {"float32": 5e-5, "bfloat16": 3e-2}
TOLERANCE_SLACK = 16.0

# ---------------------------------------------------------------------------
# the 18-failure triage (committed evidence; see module docstring)
# ---------------------------------------------------------------------------

FLASH_TEST_TRIAGE = {
    "incident": ("tests/test_flash_attention.py: 18 of 37 tests failing "
                 "under jax 0.4.37"),
    "classification": "reference_setup",
    "kernel_bug_count": 0,
    "causes": [
        {
            "symptom": "AttributeError: module 'jax' has no attribute "
                       "'typeof'",
            "where": "helpers/flash_attention.py out-shape construction",
            "root_cause": "jax.typeof (varying-mesh-axes metadata) landed "
                          "after 0.4.x; the helper assumed it "
                          "unconditionally",
            "fix": "guard: _typeof = getattr(jax, 'typeof', None); plain "
                   "ShapeDtypeStruct when absent",
        },
        {
            "symptom": "AttributeError: module 'jax.experimental.pallas."
                       "tpu' has no attribute 'CompilerParams'",
            "where": "helpers/flash_attention.py pallas_call sites (3)",
            "root_cause": "the Pallas TPU params class is TPUCompilerParams "
                          "on 0.4.x (renamed CompilerParams later)",
            "fix": "resolve whichever name exists at import time",
        },
        {
            "symptom": "ImportError: cannot import name 'shard_map' from "
                       "'jax'",
            "where": "tests/test_flash_attention.py shard_map cases (2)",
            "root_cause": "top-level jax.shard_map is post-0.4.x; 0.4.37 "
                          "exposes it via jax.experimental.shard_map",
            "fix": "import through deeplearning4j_tpu.backend.compat",
        },
    ],
    "verdict": ("all 18 failures were harness/API drift between jax "
                "versions; a per-config numerics sweep (this file) on the "
                "repaired setup shows the kernel itself within float32 "
                "tolerance on every config"),
}


# ---------------------------------------------------------------------------
# float64 numpy references (independent of the jnp implementations)
# ---------------------------------------------------------------------------

def _np_softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    m = x.max(axis=axis, keepdims=True)
    e = np.exp(x - m)
    return e / e.sum(axis=axis, keepdims=True)

def _np_attention(q, k, v, *, causal=False, window=None,
                  q_positions=None) -> np.ndarray:
    """float64 attention over [B, T, H, D] q and [B, L, Hkv, D] k/v with
    GQA head sharing, optional causal/window masking by global position,
    and optional PER-ROW query positions (the paged-decode convention:
    key index IS the global position)."""
    q = np.asarray(q, np.float64)
    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, t, hkv, g, d)
    scores = np.einsum("bqhgd,bkhd->bhgqk", qg, k) / math.sqrt(d)
    if causal:
        kpos = np.arange(k.shape[1])
        if q_positions is None:
            qpos = np.broadcast_to(np.arange(t), (b, t))
        else:
            qpos = np.asarray(q_positions)
        cm = qpos[:, :, None] >= kpos[None, None, :]        # [B, T, L]
        if window is not None:
            cm &= kpos[None, None, :] > qpos[:, :, None] - window
        scores = np.where(cm[:, None, None], scores, -1e30)
    w = _np_softmax(scores)
    o = np.einsum("bhgqk,bkhd->bqhgd", w, v)
    return o.reshape(b, t, hq, d)

def _np_gather_pages(pages, block, page_size: int) -> np.ndarray:
    pages = np.asarray(pages, np.float64)
    block = np.asarray(block)
    per = pages.reshape((-1, page_size) + pages.shape[1:])
    out = per[block]                                  # [B, MAXP, ps, ...]
    b, maxp = block.shape
    return out.reshape((b, maxp * page_size) + pages.shape[1:])

def _np_dropout_residual_norm(h, res, gamma, beta, eps, mask,
                              keep) -> np.ndarray:
    """float64 dropout(LayerNorm_affine(res + h)) — the fused train-step
    epilogue's reference (``helpers/fused_epilogue.py``)."""
    x = np.asarray(h, np.float64)
    if res is not None:
        x = x + np.asarray(res, np.float64)
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    y = ((x - mu) / np.sqrt(var + eps) * np.asarray(gamma, np.float64)
         + np.asarray(beta, np.float64))
    if mask is not None:
        y = np.where(np.asarray(mask), y / keep, 0.0)
    return y

def _np_lrn(x2d, k, n, alpha, beta) -> np.ndarray:
    x = np.asarray(x2d, np.float64)
    half = n // 2
    sq = np.pad(x * x, ((0, 0), (half, half)))
    win = np.zeros_like(x)
    for j in range(n):
        win += sq[:, j:j + x.shape[1]]
    return x / np.power(k + alpha * win, beta)

def _np_bn_inference(x2d, mean, var, gamma, beta, eps) -> np.ndarray:
    x = np.asarray(x2d, np.float64)
    inv = 1.0 / np.sqrt(np.asarray(var, np.float64) + eps)
    return ((x - np.asarray(mean, np.float64)) * inv
            * np.asarray(gamma, np.float64) + np.asarray(beta, np.float64))

def _np_bn_training(x2d, gamma, beta, eps):
    x = np.asarray(x2d, np.float64)
    mean = x.mean(0)
    var = ((x - mean) ** 2).mean(0)
    y = ((x - mean) / np.sqrt(var + eps) * np.asarray(gamma, np.float64)
         + np.asarray(beta, np.float64))
    return y, mean, var


# ---------------------------------------------------------------------------
# error measurement
# ---------------------------------------------------------------------------

def _bits(a: np.ndarray, dtype: str) -> np.ndarray:
    """Sign-ordered integer ordinals of float values in ``dtype`` — the
    space in which ``|ord(a) - ord(b)|`` counts representable values
    between a and b (ULP distance)."""
    if dtype == "bfloat16":
        import ml_dtypes
        raw = np.asarray(a, ml_dtypes.bfloat16).view(np.uint16)
        sign = np.int64(1) << 15
    else:
        raw = np.asarray(a, np.float32).view(np.uint32)
        sign = np.int64(1) << 31
    b = raw.astype(np.int64)
    # negative floats (sign bit set) map below zero, -0.0 coincides with
    # +0.0's neighborhood: ordinal(-x) = sign - bits(x)
    return np.where(b >= sign, sign - b, b)

def measure(out, ref64: np.ndarray, dtype: str) -> Dict[str, float]:
    """Error stats of one kernel output vs its float64 reference.

    The headline ``max_rel_error`` is SCALE-NORMALIZED: max-abs
    difference over the reference's max-abs value.  Elementwise
    ``diff/|ref|`` is the wrong metric here — attention outputs are
    weighted averages with near-zero elements whose relative error is
    unbounded even for a perfect-to-the-ULP kernel.  ULP distance is
    measured against the reference ROUNDED to the output dtype (the
    best any ``dtype`` kernel could do); ``ulp_p99`` is the robust
    summary, ``ulp_max`` inherits the same near-zero caveat."""
    o = np.asarray(jax.device_get(out), np.float64)
    r = np.asarray(ref64, np.float64)
    diff = np.abs(o - r)
    max_ref = float(np.abs(r).max()) if r.size else 0.0
    ulp = np.abs(_bits(o, dtype) - _bits(r, dtype))
    return {
        "max_abs_error": float(diff.max()) if diff.size else 0.0,
        "max_rel_error": (float(diff.max() / (max_ref + 1e-30))
                          if diff.size else 0.0),
        "ulp_max": int(ulp.max()) if ulp.size else 0,
        "ulp_p99": float(np.percentile(ulp, 99)) if ulp.size else 0.0,
        "ref_max_abs": max_ref,
    }


# ---------------------------------------------------------------------------
# the sweep grid
# ---------------------------------------------------------------------------

def _rng(*shape, dtype, seed):
    x = np.random.default_rng(seed).standard_normal(shape)
    return jnp.asarray(x, dtype)

def _flash_configs(full: bool):
    shapes = [(1, 128, 2, 32), (2, 128, 2, 64)]
    if full:
        shapes += [(1, 256, 4, 32), (2, 256, 2, 128)]
    for b, t, h, d in shapes:
        for dtype in ("float32", "bfloat16"):
            for causal, window in ((False, None), (True, None), (True, 64)):
                yield {"shape": [b, t, h, d], "dtype": dtype,
                       "causal": causal, "window": window}

def _run_flash(cfg) -> Tuple[Any, np.ndarray]:
    from deeplearning4j_tpu.helpers.flash_attention import flash_attention
    b, t, h, d = cfg["shape"]
    dt = jnp.dtype(cfg["dtype"])
    q = _rng(b, t, h, d, dtype=dt, seed=0)
    k = _rng(b, t, h, d, dtype=dt, seed=1)
    v = _rng(b, t, h, d, dtype=dt, seed=2)
    out = flash_attention(q, k, v, causal=cfg["causal"],
                          window=cfg["window"], interpret=True)
    ref = _np_attention(q, k, v, causal=cfg["causal"], window=cfg["window"])
    return out, ref

def _dpa_configs(full: bool):
    # the einsum path itself, incl. GQA head grouping vs the f64 reference
    shapes = [(2, 48, 4, 2, 32)]           # (B, T, Hq, Hkv, D)
    if full:
        shapes += [(1, 96, 8, 2, 64), (2, 64, 4, 4, 32)]
    for b, t, hq, hkv, d in shapes:
        for dtype in ("float32", "bfloat16"):
            for causal, window in ((False, None), (True, None), (True, 16)):
                yield {"shape": [b, t, hq, hkv, d], "dtype": dtype,
                       "causal": causal, "window": window}

def _run_dpa(cfg) -> Tuple[Any, np.ndarray]:
    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
    b, t, hq, hkv, d = cfg["shape"]
    dt = jnp.dtype(cfg["dtype"])
    q = _rng(b, t, hq, d, dtype=dt, seed=0)
    k = _rng(b, t, hkv, d, dtype=dt, seed=1)
    v = _rng(b, t, hkv, d, dtype=dt, seed=2)
    out = dot_product_attention(q, k, v, causal=cfg["causal"],
                                window=cfg["window"])
    ref = _np_attention(q, k, v, causal=cfg["causal"], window=cfg["window"])
    return out, ref

def _paged_configs(full: bool):
    grids = [{"pages": 8, "page_size": 16, "hq": 4, "hkv": 2, "d": 32,
              "b": 2, "t": 1}]
    if full:
        grids += [{"pages": 16, "page_size": 8, "hq": 4, "hkv": 4, "d": 64,
                   "b": 3, "t": 2}]
    for g in grids:
        for dtype in ("float32", "bfloat16"):
            yield dict(g, dtype=dtype)

def _run_gather(cfg) -> Tuple[Any, np.ndarray]:
    from deeplearning4j_tpu.nn.layers.attention import gather_pages
    dt = jnp.dtype(cfg["dtype"])
    pool = _rng(cfg["pages"] * cfg["page_size"], cfg["hkv"], cfg["d"],
                dtype=dt, seed=3)
    rng = np.random.default_rng(4)
    block = jnp.asarray(
        rng.integers(0, cfg["pages"], size=(cfg["b"], 4)), jnp.int32)
    out = gather_pages(pool, block, cfg["page_size"])
    return out, _np_gather_pages(pool, block, cfg["page_size"])

def _run_paged_attention(cfg) -> Tuple[Any, np.ndarray]:
    from deeplearning4j_tpu.nn.layers.attention import paged_attention
    dt = jnp.dtype(cfg["dtype"])
    L = 4 * cfg["page_size"]
    q = _rng(cfg["b"], cfg["t"], cfg["hq"], cfg["d"], dtype=dt, seed=0)
    k = _rng(cfg["b"], L, cfg["hkv"], cfg["d"], dtype=dt, seed=1)
    v = _rng(cfg["b"], L, cfg["hkv"], cfg["d"], dtype=dt, seed=2)
    rng = np.random.default_rng(5)
    qpos = np.sort(rng.integers(0, L, size=(cfg["b"], cfg["t"])), axis=1)
    out = paged_attention(q, k, v, jnp.asarray(qpos, jnp.int32))
    ref = _np_attention(q, k, v, causal=True, q_positions=qpos)
    return out, ref

def _fused_paged_configs(full: bool):
    # engine-shaped grids: page 0 is the TRASH page (unassigned block-table
    # slots point at it), per-row positions are mixed, and row 0 is the
    # all-padding row (fresh slot: block all-trash, position 0)
    grids = [{"pages": 10, "page_size": 8, "maxp": 4, "hq": 4, "hkv": 2,
              "d": 32, "b": 3, "t": 1}]
    if full:
        grids += [
            # non-GQA, multi-token chunk (speculative/chunked decode shape)
            {"pages": 12, "page_size": 8, "maxp": 4, "hq": 4, "hkv": 4,
             "d": 64, "b": 2, "t": 2},
            # non-lane-multiple head dim exercises the Pallas lane padding
            {"pages": 8, "page_size": 16, "maxp": 3, "hq": 8, "hkv": 2,
             "d": 48, "b": 4, "t": 1},
        ]
    for g in grids:
        for dtype in ("float32", "bfloat16"):
            yield dict(g, dtype=dtype)

def _run_fused_paged(cfg) -> Tuple[Any, np.ndarray]:
    """Both fused impls (lax fallback AND the Pallas kernel interpreted)
    against one f64 gather+softmax reference, concatenated into a single
    flat comparison — the bn_training precedent: one registry entry
    certifies every implementation behind the seam."""
    from deeplearning4j_tpu.helpers.paged_attention import (
        paged_decode_attention)
    dt = jnp.dtype(cfg["dtype"])
    ps, maxp, t = cfg["page_size"], cfg["maxp"], cfg["t"]
    pool_k = _rng(cfg["pages"] * ps, cfg["hkv"], cfg["d"], dtype=dt, seed=20)
    pool_v = _rng(cfg["pages"] * ps, cfg["hkv"], cfg["d"], dtype=dt, seed=21)
    q = _rng(cfg["b"], t, cfg["hq"], cfg["d"], dtype=dt, seed=22)
    rng = np.random.default_rng(23)
    block = rng.integers(1, cfg["pages"], size=(cfg["b"], maxp))
    qlast = rng.integers(t - 1, maxp * ps, size=(cfg["b"],))
    qlast[0] = t - 1
    block[0] = 0                                 # all-padding trash row
    for bi in range(cfg["b"]):
        live = int(qlast[bi]) // ps + 1
        block[bi, live:] = 0                     # trash-page-0 padding
    qpos = (qlast - (t - 1))[:, None] + np.arange(t)[None]
    blockj = jnp.asarray(block, jnp.int32)
    qposj = jnp.asarray(qpos, jnp.int32)
    out_lax = paged_decode_attention(q, pool_k, pool_v, blockj, qposj,
                                     page_size=ps, impl="lax")
    out_pl = paged_decode_attention(q, pool_k, pool_v, blockj, qposj,
                                    page_size=ps, impl="pallas",
                                    interpret=True)
    gk = _np_gather_pages(pool_k, block, ps)
    gv = _np_gather_pages(pool_v, block, ps)
    ref = _np_attention(q, gk, gv, causal=True, q_positions=qpos)
    out = jnp.concatenate([out_lax.reshape(-1), out_pl.reshape(-1)])
    return out, np.concatenate([ref.reshape(-1), ref.reshape(-1)])

def _epilogue_configs(full: bool):
    shapes = [(24, 96)]
    if full:
        shapes += [(64, 128), (17, 40)]          # incl. pad-heavy odd shape
    for m, c in shapes:
        for dtype in ("float32", "bfloat16"):
            for variant in ("residual_dropout", "prologue", "norm_only"):
                yield {"shape": [m, c], "dtype": dtype, "variant": variant}

def _run_epilogue(cfg) -> Tuple[Any, np.ndarray]:
    from deeplearning4j_tpu.helpers.fused_epilogue import (
        dropout_residual_norm)
    m, c = cfg["shape"]
    dt = jnp.dtype(cfg["dtype"])
    h = _rng(m, c, dtype=dt, seed=30)
    gamma = _rng(c, dtype=jnp.float32, seed=32)
    beta = _rng(c, dtype=jnp.float32, seed=33)
    variant = cfg["variant"]
    res = (_rng(m, c, dtype=dt, seed=31)
           if variant == "residual_dropout" else None)
    mask, keep, rate = None, 1.0, 0.0
    if variant != "norm_only":
        keep, rate = 0.75, 0.25
        # explicit mask so the f64 reference sees the exact keep pattern
        mask = jnp.asarray(
            np.random.default_rng(34).random((m, c)) < keep)
    out = dropout_residual_norm(h, res, gamma, beta, eps=1e-5, rate=rate,
                                mask=mask)
    ref = _np_dropout_residual_norm(
        h, res, gamma, beta, 1e-5,
        np.asarray(mask) if mask is not None else None, keep)
    return out, ref

def _pallas2d_configs(full: bool):
    shapes = [(32, 24)]
    if full:
        shapes += [(64, 48), (17, 5)]      # incl. a pad-heavy odd shape
    for m, c in shapes:
        yield {"shape": [m, c], "dtype": "float32"}

def _run_lrn(cfg) -> Tuple[Any, np.ndarray]:
    from deeplearning4j_tpu.helpers.pallas_ops import lrn
    m, c = cfg["shape"]
    x = _rng(m, c, dtype=jnp.float32, seed=6)
    out = lrn(x, 2.0, 5, 1e-4, 0.75)
    return out, _np_lrn(x, 2.0, 5, 1e-4, 0.75)

def _run_bn_inference(cfg) -> Tuple[Any, np.ndarray]:
    from deeplearning4j_tpu.helpers.pallas_ops import bn_inference
    m, c = cfg["shape"]
    x = _rng(m, c, dtype=jnp.float32, seed=7)
    mean = _rng(c, dtype=jnp.float32, seed=8)
    var = jnp.abs(_rng(c, dtype=jnp.float32, seed=9)) + 0.1
    gamma = _rng(c, dtype=jnp.float32, seed=10)
    beta = _rng(c, dtype=jnp.float32, seed=11)
    out = bn_inference(x, mean, var, gamma, beta, 1e-5)
    return out, _np_bn_inference(x, mean, var, gamma, beta, 1e-5)

def _run_bn_training(cfg) -> Tuple[Any, np.ndarray]:
    from deeplearning4j_tpu.helpers.pallas_ops import bn_training
    m, c = cfg["shape"]
    x = _rng(m, c, dtype=jnp.float32, seed=12)
    gamma = _rng(c, dtype=jnp.float32, seed=13)
    beta = _rng(c, dtype=jnp.float32, seed=14)
    y, mean, var = bn_training(x, gamma, beta, 1e-5)
    ry, rm, rv = _np_bn_training(x, gamma, beta, 1e-5)
    # one flat comparison covers the output AND both returned moments
    out = jnp.concatenate([y.reshape(-1), mean, var])
    ref = np.concatenate([ry.reshape(-1), rm, rv])
    return out, ref

# kernel registry: name -> (config generator, runner, exact?)
KERNELS: Dict[str, Tuple[Callable, Callable, bool]] = {
    "flash_attention": (_flash_configs, _run_flash, False),
    "dot_product_attention": (_dpa_configs, _run_dpa, False),
    "gather_pages": (_paged_configs, _run_gather, True),
    "paged_attention": (_paged_configs, _run_paged_attention, False),
    "fused_paged_attention": (_fused_paged_configs, _run_fused_paged, False),
    "fused_dropout_residual_norm": (_epilogue_configs, _run_epilogue, False),
    "pallas_lrn": (_pallas2d_configs, _run_lrn, False),
    "pallas_bn_inference": (_pallas2d_configs, _run_bn_inference, False),
    "pallas_bn_training": (_pallas2d_configs, _run_bn_training, False),
}


# ---------------------------------------------------------------------------
# classification + report
# ---------------------------------------------------------------------------

_SETUP_ERRORS = (ImportError, AttributeError, NotImplementedError)

def _config_status(stats: Dict[str, float], dtype: str,
                   exact: bool) -> str:
    budget = 0.0 if exact else DTYPE_BUDGET[dtype]
    err = stats["max_rel_error"]
    if err <= budget:
        return "pass"
    if budget and err <= TOLERANCE_SLACK * budget:
        return "tolerance_only"
    return "fail"

def classify(configs: List[Dict[str, Any]]) -> str:
    """Kernel-level divergence class from its per-config results (see
    module docstring for the taxonomy)."""
    statuses = [c["status"] for c in configs]
    if statuses and all(s == "error" for s in statuses):
        return "reference_setup"
    if "fail" in statuses:
        by_dtype: Dict[str, set] = {}
        for c in configs:
            by_dtype.setdefault(c.get("dtype", "float32"),
                                set()).add(c["status"])
        for sts in by_dtype.values():
            if "fail" in sts and "pass" in sts:
                return "shape_dependent"
        return "kernel_divergence"
    if "tolerance_only" in statuses:
        return "tolerance_only"
    return "within_tolerance"

def run_sweep(kernels: Optional[Sequence[str]] = None,
              full: bool = False) -> Dict[str, Any]:
    """Run the differential grid and build the kernel_trust document."""
    report: Dict[str, Any] = {"schema": 1, "platform": jax.devices()[0]
                              .platform, "jax_version": jax.__version__,
                              "dtype_budgets": dict(DTYPE_BUDGET),
                              "kernels": {}, "all": []}
    for name in (kernels or KERNELS):
        gen, run, exact = KERNELS[name]
        entries: List[Dict[str, Any]] = []
        for cfg in gen(full):
            entry = dict(cfg)
            try:
                out, ref = run(cfg)
                entry.update(measure(out, ref, cfg["dtype"]))
                entry["status"] = _config_status(entry, cfg["dtype"], exact)
            except _SETUP_ERRORS as e:
                entry.update(status="error", classification=(
                    "reference_setup"), error=f"{type(e).__name__}: {e}")
            entries.append(entry)
        cls = classify(entries)
        measured = [e for e in entries if "max_rel_error" in e]
        worst = (max(measured, key=lambda e: e["max_rel_error"])
                 if measured else None)
        kd = {
            "configs": entries,
            "classification": cls,
            "trusted": cls in ("within_tolerance", "tolerance_only"),
            "max_rel_error": worst["max_rel_error"] if worst else None,
            "worst_config": ({k: worst[k] for k in
                              ("shape", "dtype", "causal", "window",
                               "variant", "page_size", "pages")
                              if k in worst} if worst else None),
        }
        report["kernels"][name] = kd
        if worst is not None:
            report["all"].append({
                "metric": f"Kernel max rel error ({name})",
                "value": worst["max_rel_error"],
                "unit": "rel", "classification": cls,
                "configs": len(entries),
                "failing_configs": sum(
                    1 for e in entries if e["status"] == "fail"),
            })
    report["summary"] = {
        "kernels": len(report["kernels"]),
        "untrusted": sorted(n for n, k in report["kernels"].items()
                            if not k["trusted"]),
        "failing_configs": sum(
            e.get("failing_configs", 0) for e in report["all"]),
    }
    report["triage"] = {"flash_attention_tests": FLASH_TEST_TRIAGE}
    return report

def publish_metrics(report: Dict[str, Any], registry=None) -> None:
    """Mirror each kernel's worst-config error into the gauge family."""
    if registry is None:
        from deeplearning4j_tpu.observability import get_registry
        registry = get_registry()
    g = registry.gauge(
        _KERNEL_ERR, "Worst-config max relative error of each fused "
        "kernel vs its float64 reference, from the most recent "
        "kernel-trust sweep (observability.kerneldiff)",
        labels=("kernel",))
    for name, k in report["kernels"].items():
        if k["max_rel_error"] is not None:
            g.set(k["max_rel_error"], kernel=name)

def format_report(report: Dict[str, Any]) -> str:
    lines = [f"kernel trust sweep ({report['platform']}, "
             f"jax {report['jax_version']})"]
    for name, k in report["kernels"].items():
        err = (f"{k['max_rel_error']:.3g}"
               if k["max_rel_error"] is not None else "n/a")
        lines.append(
            f"  {'ok ' if k['trusted'] else 'BAD'} {name:<24} "
            f"max_rel={err:<10} {k['classification']} "
            f"({len(k['configs'])} configs)")
    return "\n".join(lines)


def check_registry(trust_path: str) -> int:
    """CI gate: every kernel in the committed trust document must exist
    in this registry and vice versa — a fused kernel that is not swept
    has no claim to trust, and a trust entry with no surviving kernel is
    stale evidence.  Returns a nonzero exit code on any mismatch."""
    with open(trust_path) as f:
        doc = json.load(f)
    in_doc = set(doc.get("kernels", {}))
    in_reg = set(KERNELS)
    rc = 0
    for name in sorted(in_reg - in_doc):
        print(f"kernel '{name}' is registered in kerneldiff but absent "
              f"from {trust_path} — regenerate the trust document "
              "(python -m deeplearning4j_tpu.observability.kerneldiff "
              f"--full --out {trust_path})", file=sys.stderr)
        rc = 1
    for name in sorted(in_doc - in_reg):
        print(f"kernel '{name}' appears in {trust_path} but has no "
              "kerneldiff registry entry — its trust evidence is stale",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print(f"registry <-> {trust_path} consistent "
              f"({len(in_reg)} kernels)")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default=None, help="write kernel_trust.json")
    ap.add_argument("--full", action="store_true",
                    help="full grid (default: quick CPU grid)")
    ap.add_argument("--kernels", default=None,
                    help="comma-separated subset of kernels")
    ap.add_argument("--baseline", default=None,
                    help="compare against a committed kernel_trust.json "
                         "with regression.KERNEL_TRUST_RULES")
    ap.add_argument("--check-registry", default=None, metavar="PATH",
                    help="no sweep: verify the committed trust document "
                         "and this registry list the same kernels")
    args = ap.parse_args(argv)
    if args.check_registry:
        return check_registry(args.check_registry)
    names = args.kernels.split(",") if args.kernels else None
    report = run_sweep(kernels=names, full=args.full)
    publish_metrics(report)
    print(format_report(report))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"wrote {args.out}")
    rc = 0
    if args.baseline:
        from deeplearning4j_tpu.observability import regression
        with open(args.baseline) as f:
            base = json.load(f)
        rep = regression.compare(base, report,
                                 regression.KERNEL_TRUST_RULES)
        print(rep.format())
        rc = rep.exit_code
    if report["summary"]["untrusted"]:
        print(f"UNTRUSTED kernels: {report['summary']['untrusted']}",
              file=sys.stderr)
        rc = rc or 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
