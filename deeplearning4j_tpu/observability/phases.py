"""Registry-backed phase timers — the ``PhaseStats`` successor.

≙ ``CommonSparkTrainingStats.java`` / ``ParameterAveragingTrainingMasterStats
.java``: the reference times count/split/repartition/mapPartitions/aggregate
per fit; here the phases are the TPU-native pipeline sections (fetch /
place / dispatch / device_sync, gradient compute vs all-reduce vs host
sync).

Each timed phase is recorded twice:

- into a per-instance ``Histogram`` so ``as_dict()`` keeps the exact
  ``PhaseStats`` schema (count/total_ms/mean_ms/min_ms/max_ms per phase)
  that ``training_stats()`` consumers and tests rely on;
- into the process-wide registry family
  ``dl4j_phase_seconds{component=..., phase=...}`` so /metrics scrapes and
  bench snapshots see phase timing without holding a master reference.

Migration from the old private ``PhaseStats``: the class below is a drop-in
(same ``phase()`` context manager, ``steps`` counter, ``enabled`` flag,
``as_dict()``), re-exported from ``parallel.training_master`` under the old
name.  See docs/observability.md.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from deeplearning4j_tpu.observability.metrics import (
    Histogram, MetricsRegistry, get_registry,
)

_FAMILY = "dl4j_phase_seconds"


class _NullTimer:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullTimer()


class _Timer:
    __slots__ = ("_local", "_shared", "_t0")

    def __init__(self, local: Histogram, shared):
        self._local = local
        self._shared = shared

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._local.observe(dt)
        if self._shared is not None:
            self._shared.observe(dt)
        return False


class PhaseTimers:
    """Phase-timed stats for one component instance (see module doc)."""

    def __init__(self, component: str, enabled: bool = True,
                 registry: Optional[MetricsRegistry] = None):
        self.component = component
        self.enabled = enabled
        self.steps = 0
        self._registry = registry
        self._local: Dict[str, Histogram] = {}
        self._shared: Dict[str, Any] = {}
        self._shared_reg: Optional[MetricsRegistry] = None

    def phase(self, name: str):
        if not self.enabled:
            return _NULL
        reg = (self._registry if self._registry is not None
               else get_registry())
        if reg is not self._shared_reg or reg.get(_FAMILY) is None:
            # registry swapped (set_registry) or wiped (reset()): drop the
            # shared children so timings land in the LIVE registry; the
            # per-instance _local aggregates (as_dict) carry on unbroken
            self._shared.clear()
            self._shared_reg = reg
        local = self._local.get(name)
        if local is None:
            local = self._local[name] = Histogram()
        if name not in self._shared:
            self._shared[name] = reg.histogram(
                _FAMILY, "Per-phase wall time of distributed-training and "
                "pipeline components", labels=("component", "phase"),
            ).labels(component=self.component, phase=name)
        return _Timer(local, self._shared.get(name))

    def totals(self) -> Dict[str, float]:
        """Cumulative seconds per phase — cheap enough to snapshot before/
        after a batch for per-batch phase deltas (pipeline per-stage
        attribution)."""
        return {name: h.sum for name, h in self._local.items()}

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"steps": self.steps, "phases": {}}
        for name, h in self._local.items():
            if not h.count:
                continue
            out["phases"][name] = {
                "count": h.count,
                "total_ms": round(h.sum * 1e3, 3),
                "mean_ms": round(h.sum / h.count * 1e3, 3),
                "min_ms": round(h.min * 1e3, 3),
                "max_ms": round(h.max * 1e3, 3),
            }
        return out
