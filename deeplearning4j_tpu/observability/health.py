"""Cluster health: per-worker aggregation, straggler detection, SLO rules.

SparkNet (arxiv 1511.06051) and DeepSpark (arxiv 1602.08191) both observe
that synchronous distributed training runs at the speed of the SLOWEST
replica — so the first diagnostic question for "this 8-worker run is slow"
is *which worker*, and the reference stack answered it with Spark's
driver-side stage timing.  This module is that layer on top of the PR-1
telemetry core:

- ``WorkerTelemetry`` — what the training masters publish into: per-worker
  (or per-pipeline-stage) step time and throughput as labeled registry
  families, plus a rolling sample window per worker;
- ``StragglerDetector`` — flags a worker whose rolling median step time
  exceeds ``threshold`` x the cluster median, counts it in
  ``dl4j_stragglers_total{component,worker}``, and emits one rate-limited
  warning carrying the offending phase breakdown;
- ``ClusterStatsAggregator`` — merges per-worker snapshots (plain dicts,
  so they travel across processes as JSON) into one cluster view:
  mean/p50/p99/max step time, slowest worker id, total throughput;
- ``HealthEvaluator`` — declarative SLO rules (max step-time p99, max
  queue depth, min throughput, recompile budget, ...) evaluated against
  the registry; powers the ``GET /health`` endpoints on the inference
  server and the training UI server.

Everything here reads metrics the hot loops already record; nothing in
this module runs on the dispatch path.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.observability.metrics import (
    Histogram, MetricsRegistry, get_registry,
)

_STEP = "dl4j_worker_step_seconds"
_TPUT = "dl4j_worker_samples_per_second"
_STRAGGLERS = "dl4j_stragglers_total"
_HEALTH = "dl4j_health_status"

logger = logging.getLogger("deeplearning4j_tpu.observability")


def _median(values: Sequence[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    if not n:
        return float("nan")
    mid = n // 2
    return vs[mid] if n % 2 else 0.5 * (vs[mid - 1] + vs[mid])


def _quantile(values: Sequence[float], q: float) -> float:
    vs = sorted(values)
    if not vs:
        return float("nan")
    pos = q * (len(vs) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)


def histogram_quantile(hist: Histogram, q: float) -> float:
    """Prometheus-style quantile from cumulative buckets (linear
    interpolation within the containing bucket; NaN on an empty
    histogram).  An upper-bound estimate capped at the observed max when
    the quantile lands in the +Inf bucket."""
    snap = hist.snapshot()
    count = snap["count"]
    if not count:
        return float("nan")
    rank = q * count
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in snap["cumulative_buckets"]:
        if cum >= rank:
            if math.isinf(bound):
                return snap["max"]
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = bound, cum
    return snap["max"]


# ------------------------------------------------------------- stragglers
class StragglerDetector:
    """Rolling-window straggler detection for one component's workers.

    A worker is flagged when its rolling median step time exceeds
    ``threshold`` times the cluster median (the median of the OTHER
    workers' rolling medians — excluding the candidate keeps a straggler
    from dragging the reference toward itself, which in a 2-worker
    cluster would make the criterion unsatisfiable) — the
    SparkNet/DeepSpark slow-replica criterion —
    AND the absolute excess over the cluster median is at least
    ``min_excess_s``.  The absolute floor keeps sub-millisecond jitter
    (host scheduling noise on fast steps) from pattern-matching as a
    straggler: a worker "2x slower" by 40 microseconds is not an
    actionable fix.  Every flagged observation increments
    ``dl4j_stragglers_total{component,worker}``; the WARNING (with the
    phase breakdown of the offending worker, when the caller provides
    one) is rate-limited to one per ``warn_interval_s`` per worker.
    """

    def __init__(self, component: str, threshold: float = 2.0,
                 window: int = 32, min_steps: int = 4,
                 min_excess_s: float = 0.010,
                 warn_interval_s: float = 30.0, registry=None,
                 warn: Optional[Callable[[str], None]] = None):
        if threshold <= 1.0:
            raise ValueError(f"threshold must be > 1.0, got {threshold}")
        self.component = component
        self.threshold = float(threshold)
        self.min_excess_s = float(min_excess_s)
        self.window = int(window)
        self.min_steps = max(2, int(min_steps))
        self.warn_interval_s = float(warn_interval_s)
        self.warn = warn or logger.warning
        self._lock = threading.Lock()
        self._windows: Dict[str, deque] = {}
        self._last_warn: Dict[str, float] = {}
        self.flag_counts: Dict[str, int] = {}
        reg = registry if registry is not None else get_registry()
        self._m_stragglers = reg.counter(
            _STRAGGLERS, "Straggler observations: a worker/stage whose "
            "rolling median step time exceeded the configured multiple of "
            "the cluster median", labels=("component", "worker"))

    def observe(self, worker, seconds: float,
                phases: Optional[Dict[str, float]] = None) -> bool:
        """Record one step time for ``worker``; returns True when this
        observation flags the worker as a straggler."""
        worker = str(worker)
        with self._lock:
            win = self._windows.get(worker)
            if win is None:
                win = self._windows[worker] = deque(maxlen=self.window)
            win.append(float(seconds))
            if len(self._windows) < 2:
                return False
            medians = {w: _median(win) for w, win in self._windows.items()
                       if len(win) >= self.min_steps}
            if len(medians) < 2 or worker not in medians:
                return False
            # cluster reference EXCLUDES this worker: including it lets a
            # straggler drag the median toward itself — with 2 workers
            # 'mine > 2x median(mine, other)' is unsatisfiable, so a slow
            # half of a 2-replica/2-stage cluster would never be named
            mine = medians[worker]
            cluster = _median([m for w, m in medians.items() if w != worker])
            if (not (cluster > 0) or mine <= self.threshold * cluster
                    or mine - cluster < self.min_excess_s):
                return False
            self.flag_counts[worker] = self.flag_counts.get(worker, 0) + 1
            now = time.monotonic()
            should_warn = (now - self._last_warn.get(worker, -math.inf)
                           >= self.warn_interval_s)
            if should_warn:
                self._last_warn[worker] = now
        self._m_stragglers.inc(component=self.component, worker=worker)
        if should_warn:
            try:
                # performance attribution: a straggler verdict arms the
                # installed StepProfiler to capture the next step, so the
                # trace shows what the degraded window actually did.
                # Gated on the rate-limited warning path: a persistently
                # slow worker must not re-arm a capture every window.
                from deeplearning4j_tpu.observability import profiling

                profiling.notify_straggler(self.component, worker)
            except Exception:
                pass
        if should_warn:
            breakdown = ""
            if phases:
                parts = ", ".join(f"{k}={v * 1e3:.1f}ms"
                                  for k, v in phases.items())
                breakdown = f" (phases: {parts})"
            self.warn(
                f"straggler in {self.component}: worker {worker} rolling "
                f"median step {mine * 1e3:.1f}ms > {self.threshold:.1f}x "
                f"cluster median {cluster * 1e3:.1f}ms{breakdown}")
        return True

    def stragglers(self) -> Dict[str, int]:
        """worker -> times flagged (empty when the cluster is healthy)."""
        with self._lock:
            return dict(self.flag_counts)


class WorkerTelemetry:
    """Per-worker/per-stage publication seam for one component.

    ``observe(worker, seconds, ...)`` lands in
    ``dl4j_worker_step_seconds{component,worker}`` (histogram) and
    ``dl4j_worker_samples_per_second{component,worker}`` (gauge), keeps a
    rolling sample window per worker for ``snapshot()``, and feeds the
    attached ``StragglerDetector``."""

    def __init__(self, component: str, registry=None,
                 detector: Optional[StragglerDetector] = None,
                 threshold: float = 2.0, window: int = 32,
                 min_steps: int = 4, min_excess_s: float = 0.010):
        reg = registry if registry is not None else get_registry()
        self.component = component
        self.step_seconds = reg.histogram(
            _STEP, "Per-worker (or per-pipeline-stage) step time published "
            "by the training masters", labels=("component", "worker"))
        self.throughput = reg.gauge(
            _TPUT, "Per-worker throughput implied by the most recent step",
            labels=("component", "worker"))
        self.detector = detector or StragglerDetector(
            component, threshold=threshold, window=window,
            min_steps=min_steps, min_excess_s=min_excess_s, registry=reg)
        self._lock = threading.Lock()
        self._windows: Dict[str, deque] = {}
        self._last: Dict[str, Dict[str, Any]] = {}

    def observe(self, worker, seconds: float, batch: Optional[int] = None,
                phases: Optional[Dict[str, float]] = None) -> bool:
        worker = str(worker)
        seconds = float(seconds)
        self.step_seconds.observe(seconds, component=self.component,
                                  worker=worker)
        sps = None
        if batch and seconds > 0:
            sps = batch / seconds
            self.throughput.set(sps, component=self.component, worker=worker)
        with self._lock:
            win = self._windows.get(worker)
            if win is None:
                win = self._windows[worker] = deque(maxlen=64)
            win.append(seconds)
            self._last[worker] = {"seconds": seconds,
                                  "samples_per_second": sps}
        return self.detector.observe(worker, seconds, phases=phases)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Per-worker summaries as plain dicts (JSON-safe, mergeable
        across processes by ``ClusterStatsAggregator.merge``)."""
        with self._lock:
            items = [(w, list(win)) for w, win in self._windows.items()]
            last = dict(self._last)
        out = []
        for worker, samples in sorted(items):
            out.append({
                "worker": worker,
                "count": len(samples),
                "mean": sum(samples) / len(samples) if samples else None,
                "p50": _median(samples),
                "p99": _quantile(samples, 0.99),
                "max": max(samples) if samples else None,
                "last": last.get(worker, {}).get("seconds"),
                "samples_per_second":
                    last.get(worker, {}).get("samples_per_second"),
                "samples": samples,
            })
        return out

    def cluster_view(self) -> Dict[str, Any]:
        return ClusterStatsAggregator.merge(self.snapshot())


class ClusterStatsAggregator:
    """Merges per-worker snapshot dicts into one cluster view.

    Works on plain dicts so multi-process deployments can ship each
    process's ``WorkerTelemetry.snapshot()`` as JSON and merge driver-side
    (the Spark-driver stage-timing pattern without the driver in the data
    path)."""

    #: schema tag a wire-delivered snapshot MAY carry.  Absent = legacy
    #: in-process snapshot (accepted); equal = accepted; anything else
    #: was produced by a worker this process does not understand and is
    #: skipped with a log line, never raised on.
    SNAPSHOT_SCHEMA = 1

    @staticmethod
    def _usable(s: Any) -> bool:
        """Tolerant per-snapshot gate for wire-delivered dicts from
        heterogeneous workers: non-dicts, mismatched schema tags and
        unparseable counts are log-and-skip; unknown extra keys ride
        through untouched; a missing/zero count is silently empty
        (pre-existing semantics)."""
        if not isinstance(s, dict):
            if s:   # None/{} stay silent — the legacy empty-slot case
                logger.warning("cluster merge: skipping non-dict "
                               "snapshot (%s)", type(s).__name__)
            return False
        schema = s.get("schema", ClusterStatsAggregator.SNAPSHOT_SCHEMA)
        if schema != ClusterStatsAggregator.SNAPSHOT_SCHEMA:
            logger.warning("cluster merge: skipping snapshot from %r "
                           "with unknown schema %r",
                           s.get("worker"), schema)
            return False
        count = s.get("count")
        if count is None or count == 0:
            return False
        if not isinstance(count, (int, float)) or isinstance(count, bool):
            logger.warning("cluster merge: skipping snapshot from %r "
                           "with unparseable count %r",
                           s.get("worker"), count)
            return False
        return True

    @staticmethod
    def _f(v: Any) -> Optional[float]:
        """A numeric field or None — wire snapshots may carry anything."""
        return float(v) if (isinstance(v, (int, float))
                            and not isinstance(v, bool)) else None

    @staticmethod
    def merge(snapshots: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        _f = ClusterStatsAggregator._f
        snapshots = [s for s in (snapshots or ())
                     if ClusterStatsAggregator._usable(s)]
        pooled: List[float] = []
        throughput = 0.0
        has_tput = False
        slowest = None
        for s in snapshots:
            samples = s.get("samples")
            if isinstance(samples, (list, tuple)):
                pooled.extend(v for v in map(_f, samples)
                              if v is not None)
            sps = _f(s.get("samples_per_second"))
            if sps:
                throughput += sps
                has_tput = True
            if slowest is None or (_f(s.get("mean")) or 0) > (
                    _f(slowest.get("mean")) or 0):
                slowest = s
        view: Dict[str, Any] = {
            "workers": len(snapshots),
            "steps": int(sum(s["count"] for s in snapshots)),
            "slowest_worker": slowest.get("worker") if slowest else None,
            "samples_per_second_total": throughput if has_tput else None,
            "per_worker": [
                {k: v for k, v in s.items() if k != "samples"}
                for s in snapshots
            ],
        }
        if pooled:
            view["step_seconds"] = {
                "mean": sum(pooled) / len(pooled),
                "p50": _median(pooled),
                "p99": _quantile(pooled, 0.99),
                "max": max(pooled),
            }
        return view

    @staticmethod
    def from_registry(registry: Optional[MetricsRegistry] = None,
                      component: Optional[str] = None) -> Dict[str, Any]:
        """Cluster view reconstructed from the shared registry's
        ``dl4j_worker_step_seconds`` children (useful when the master
        object is out of reach, e.g. from a /health handler)."""
        reg = registry if registry is not None else get_registry()
        fam = reg.get(_STEP)
        snapshots = []
        if fam is not None:
            for label_pairs, child in fam.samples():
                labels = dict(label_pairs)
                if component and labels.get("component") != component:
                    continue
                snap = child.snapshot()
                if not snap["count"]:
                    continue
                snapshots.append({
                    "worker": labels.get("worker"),
                    "component": labels.get("component"),
                    "count": snap["count"],
                    "mean": snap["sum"] / snap["count"],
                    "p50": histogram_quantile(child, 0.5),
                    "p99": histogram_quantile(child, 0.99),
                    "max": snap["max"],
                    "samples": [],
                })
        pooled_view = ClusterStatsAggregator.merge(snapshots)
        # histograms carry no raw samples; synthesize the cluster step
        # stats from the per-worker quantiles instead of the empty pool
        if snapshots:
            pooled_view["step_seconds"] = {
                "mean": (sum(s["mean"] * s["count"] for s in snapshots)
                         / sum(s["count"] for s in snapshots)),
                "p50": _median([s["p50"] for s in snapshots]),
                "p99": max(s["p99"] for s in snapshots),
                "max": max(s["max"] for s in snapshots),
            }
        return pooled_view


# ------------------------------------------------------------------ health
class HealthRule:
    """One declarative SLO rule evaluated against the registry.

    Kinds (``metric`` defaults in parentheses):

    - ``max_step_p99`` — p99 of a step-time histogram, max over children
      (``dl4j_fit_step_seconds``) must be <= ``limit`` seconds
    - ``max_queue_depth`` — max gauge child (``dl4j_serving_queue_depth``)
      must be <= ``limit``
    - ``min_throughput`` — max gauge child
      (``dl4j_fit_samples_per_second``) must be >= ``limit``
    - ``max_recompiles`` — summed counter (``dl4j_recompiles_total``)
      must be <= ``limit``
    - ``max_stragglers`` — summed counter (``dl4j_stragglers_total``)
      must be <= ``limit``
    - ``max_checkpoint_staleness`` — max gauge child
      (``dl4j_checkpoint_staleness_seconds``) must be <= ``limit``
      seconds: flags a run whose CheckpointManager stopped committing
      (or never started) long before the lost progress is discovered
      the hard way
    - ``max_evicted_replicas`` — max gauge child
      (``dl4j_elastic_evicted_replicas``) must be <= ``limit``: a
      degraded-mode mesh (replicas evicted from the averaging collective,
      docs/resilience.md "Elasticity") is tolerable up to a budget —
      beyond it the run is limping and /health should say so
    - ``max_nonfinite_steps`` — summed counter
      (``dl4j_nonfinite_steps_total``) must be <= ``limit``: the
      stability engine's guard turns poisoned steps into no-ops, but a
      run skipping many steps is limping — budget it
      (docs/resilience.md "Stability")
    - ``max_divergence_rewinds`` — summed counter
      (``dl4j_divergence_rewinds_total``) must be <= ``limit``: every
      auto-rewind re-trains from an older checkpoint; repeated rewinds
      mean the run cannot make it past a divergence wall
    - ``max_dead_fraction`` — max gauge child
      (``dl4j_layer_dead_fraction``) must be <= ``limit``: a layer whose
      activations are (nearly) all zero is a dying-ReLU / dead-unit
      layer; the failing layer is named in the detail
      (docs/observability.md "Training introspection")
    - ``update_ratio_band`` — every ``dl4j_layer_update_ratio`` gauge
      child must lie in ``[limit_low, limit]``: the update:param norm
      ratio doctrine (~1e-3 healthy) — too low means the layer is
      frozen/vanishing, too high means the LR is about to bounce the
      weights; the worst offender is named
    - ``max_gradient_norm_ratio`` — the max:min spread over
      ``dl4j_layer_gradient_norm`` children must be <= ``limit``:
      vanishing/exploding gradients across depth, with both extreme
      layers named
    - ``predicate`` — ``fn(extra) -> bool`` (or ``(ok, observed, detail)``)
      for liveness checks that live outside the registry

    A rule with no data passes unless ``require_data=True`` — "nothing
    has trained/served yet" is healthy, "metrics stopped flowing" can be
    made a failure per rule.
    """

    _DEFAULT_METRIC = {
        "max_step_p99": "dl4j_fit_step_seconds",
        "max_queue_depth": "dl4j_serving_queue_depth",
        "min_throughput": "dl4j_fit_samples_per_second",
        "max_recompiles": "dl4j_recompiles_total",
        "max_stragglers": "dl4j_stragglers_total",
        "max_checkpoint_staleness": "dl4j_checkpoint_staleness_seconds",
        "max_evicted_replicas": "dl4j_elastic_evicted_replicas",
        "max_nonfinite_steps": "dl4j_nonfinite_steps_total",
        "max_divergence_rewinds": "dl4j_divergence_rewinds_total",
        "max_dead_fraction": "dl4j_layer_dead_fraction",
        "update_ratio_band": "dl4j_layer_update_ratio",
        "max_gradient_norm_ratio": "dl4j_layer_gradient_norm",
    }

    def __init__(self, name: str, kind: str, limit: Optional[float] = None,
                 metric: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None,
                 require_data: bool = False,
                 fn: Optional[Callable[[Any], Any]] = None,
                 limit_low: Optional[float] = None):
        if kind != "predicate" and kind not in self._DEFAULT_METRIC:
            raise ValueError(f"unknown health-rule kind {kind!r}")
        if kind == "predicate" and fn is None:
            raise ValueError("predicate rules need fn=")
        if kind != "predicate" and limit is None:
            raise ValueError(f"rule {name!r} ({kind}) needs limit=")
        if kind == "update_ratio_band":
            if limit_low is None:
                raise ValueError("update_ratio_band needs limit_low=")
            if limit_low > limit:
                raise ValueError(
                    f"limit_low {limit_low} > limit {limit}")
        self.name = name
        self.kind = kind
        self.limit = limit
        self.limit_low = limit_low
        self.metric = metric or self._DEFAULT_METRIC.get(kind)
        self.labels = dict(labels or {})
        self.require_data = require_data
        self.fn = fn

    # ---------------------------------------------------------- observation
    def _children(self, reg: MetricsRegistry):
        fam = reg.get(self.metric)
        if fam is None:
            return []
        out = []
        for label_pairs, child in fam.samples():
            labels = dict(label_pairs)
            if all(labels.get(k) == v for k, v in self.labels.items()):
                out.append((labels, child))
        return out

    def _observed(self, reg: MetricsRegistry):
        """(observed value, detail) for metric-backed kinds; observed is
        None when the family/children don't exist yet."""
        children = self._children(reg)
        if self.kind == "max_step_p99":
            vals = [(histogram_quantile(c, 0.99), labels)
                    for labels, c in children if c.count]
            vals = [(v, l) for v, l in vals if not math.isnan(v)]
            if not vals:
                return None, "no step samples yet"
            v, labels = max(vals, key=lambda t: t[0])
            return v, f"worst child: {labels or 'unlabeled'}"
        if self.kind in ("max_queue_depth", "min_throughput",
                         "max_checkpoint_staleness",
                         "max_evicted_replicas", "max_dead_fraction"):
            vals = [(c.value, labels) for labels, c in children]
            vals = [(v, l) for v, l in vals if not math.isnan(v)]
            if not vals:
                return None, "no gauge children yet"
            # all these kinds take the MAX child: deepest queue for the
            # depth cap, best current throughput for the floor (a stale
            # low gauge from a finished side model must not fail the
            # floor forever — narrow with labels= to watch one child),
            # the stalest checkpoint manager for the staleness cap, the
            # most-degraded component for the eviction budget, and the
            # most-dead layer for the dead-unit cap
            v, labels = max(vals, key=lambda t: t[0])
            which = {"max_queue_depth": "deepest",
                     "min_throughput": "best",
                     "max_checkpoint_staleness": "stalest",
                     "max_evicted_replicas": "most degraded",
                     "max_dead_fraction": "most dead"}[self.kind]
            return v, f"{which} child: {labels or 'unlabeled'}"
        if self.kind == "update_ratio_band":
            vals = [(c.value, labels) for labels, c in children
                    if not math.isnan(c.value)]
            if not vals:
                return None, "no gauge children yet"

            def badness(v):
                # multiplicative distance outside [limit_low, limit];
                # <= 1 means inside the band
                if v <= 0:
                    return math.inf
                return max(self.limit_low / v, v / self.limit)

            v, labels = max(vals, key=lambda t: badness(t[0]))
            return v, f"worst child: {labels or 'unlabeled'}"
        if self.kind == "max_gradient_norm_ratio":
            vals = [(c.value, labels) for labels, c in children
                    if math.isfinite(c.value) and c.value > 0]
            if len(vals) < 2:
                return None, "fewer than two layers with gradient norms"
            lo_v, lo_l = min(vals, key=lambda t: t[0])
            hi_v, hi_l = max(vals, key=lambda t: t[0])
            return hi_v / lo_v, (f"max {hi_l or 'unlabeled'}={hi_v:.3g} vs "
                                 f"min {lo_l or 'unlabeled'}={lo_v:.3g}")
        # counters: sum over matching children
        if not children:
            return None, "counter not registered yet"
        return sum(c.value for _, c in children), \
            f"summed over {len(children)} children"

    def evaluate(self, reg: MetricsRegistry,
                 extra: Any = None) -> Dict[str, Any]:
        if self.kind == "predicate":
            try:
                res = self.fn(extra)
            except Exception as e:
                return {"name": self.name, "kind": self.kind, "ok": False,
                        "observed": None, "limit": None,
                        "detail": f"predicate raised: {e!r}"}
            if isinstance(res, tuple):
                ok, observed, detail = (list(res) + [None, None])[:3]
            else:
                ok, observed, detail = bool(res), res, None
            return {"name": self.name, "kind": self.kind, "ok": bool(ok),
                    "observed": observed, "limit": self.limit,
                    "detail": detail}
        observed, detail = self._observed(reg)
        if observed is None:
            ok = not self.require_data
            detail = f"no data ({detail}); " + (
                "required -> fail" if self.require_data else "pass")
        elif self.kind == "min_throughput":
            ok = observed >= self.limit
        elif self.kind == "update_ratio_band":
            ok = self.limit_low <= observed <= self.limit
        else:
            ok = observed <= self.limit
        return {"name": self.name, "kind": self.kind, "ok": ok,
                "observed": observed, "limit": self.limit,
                "metric": self.metric, "detail": detail}


class HealthVerdict:
    """Outcome of one evaluation: overall flag + per-rule results."""

    def __init__(self, component: str, results: List[Dict[str, Any]]):
        self.component = component
        self.results = results
        self.healthy = all(r["ok"] for r in results)
        self.failing = [r for r in results if not r["ok"]]

    def to_dict(self) -> Dict[str, Any]:
        return {"healthy": self.healthy, "component": self.component,
                "failing": [r["name"] for r in self.failing],
                "rules": self.results}


class HealthEvaluator:
    """Evaluates a rule set against the (shared) registry and mirrors the
    verdict into ``dl4j_health_status{component}`` (1 healthy / 0 not) so
    scrapes see health flips even between /health polls."""

    def __init__(self, rules: Sequence[HealthRule], component: str = "main",
                 registry=None):
        self.rules = list(rules)
        self.component = component
        self._registry = registry

    def evaluate(self, extra: Any = None) -> HealthVerdict:
        reg = (self._registry if self._registry is not None
               else get_registry())
        verdict = HealthVerdict(
            self.component, [r.evaluate(reg, extra) for r in self.rules])
        reg.gauge(
            _HEALTH, "Most recent HealthEvaluator verdict (1 = all SLO "
            "rules passing)", labels=("component",)
        ).set(1.0 if verdict.healthy else 0.0, component=self.component)
        return verdict


def default_training_rules(max_step_p99_s: Optional[float] = None,
                           min_samples_per_sec: Optional[float] = None,
                           max_recompiles: float = 100.0,
                           max_stragglers: Optional[float] = None,
                           max_checkpoint_staleness_s: Optional[float] = None,
                           max_evicted_replicas: Optional[float] = None,
                           max_nonfinite_steps: Optional[float] = None,
                           max_divergence_rewinds: Optional[float] = None,
                           max_dead_fraction: Optional[float] = None,
                           update_ratio_band=None,
                           max_gradient_norm_ratio: Optional[float] = None,
                           ) -> List[HealthRule]:
    """Sensible defaults for a training process: an optional step-time
    SLO, an optional throughput floor, a recompile budget (steady-state
    shape churn is the classic silent TPU throughput bug), an optional
    straggler budget, an optional checkpoint-staleness cap (a run whose
    CheckpointManager stopped committing fails /health while the progress
    is still recoverable — docs/resilience.md), an optional evicted-
    replica budget (degraded-mode training past the budget fails /health
    even though the loop is still making progress), optional
    stability budgets: guarded-skip steps and divergence auto-rewinds
    (docs/resilience.md "Stability"), and optional introspection anomaly
    budgets: dead-unit fraction cap, update:param ratio band
    ``(low, high)``, and cross-layer gradient-norm spread
    (docs/observability.md "Training introspection")."""
    rules = [HealthRule("recompile_budget", "max_recompiles",
                        max_recompiles)]
    if max_step_p99_s is not None:
        rules.append(HealthRule("step_p99", "max_step_p99", max_step_p99_s))
    if min_samples_per_sec is not None:
        rules.append(HealthRule("throughput_floor", "min_throughput",
                                min_samples_per_sec))
    if max_stragglers is not None:
        rules.append(HealthRule("straggler_budget", "max_stragglers",
                                max_stragglers))
    if max_checkpoint_staleness_s is not None:
        rules.append(HealthRule("checkpoint_staleness",
                                "max_checkpoint_staleness",
                                max_checkpoint_staleness_s))
    if max_evicted_replicas is not None:
        rules.append(HealthRule("evicted_replicas", "max_evicted_replicas",
                                max_evicted_replicas))
    if max_nonfinite_steps is not None:
        rules.append(HealthRule("nonfinite_steps", "max_nonfinite_steps",
                                max_nonfinite_steps))
    if max_divergence_rewinds is not None:
        rules.append(HealthRule("divergence_rewinds",
                                "max_divergence_rewinds",
                                max_divergence_rewinds))
    # training-introspection anomaly budgets (per-layer gradient/update/
    # activation gauges published by StatsListener harvests —
    # docs/observability.md "Training introspection")
    if max_dead_fraction is not None:
        rules.append(HealthRule("dead_fraction", "max_dead_fraction",
                                max_dead_fraction))
    if update_ratio_band is not None:
        lo, hi = update_ratio_band
        rules.append(HealthRule("update_ratio_band", "update_ratio_band",
                                hi, limit_low=lo))
    if max_gradient_norm_ratio is not None:
        rules.append(HealthRule("gradient_norm_ratio",
                                "max_gradient_norm_ratio",
                                max_gradient_norm_ratio))
    return rules


def default_serving_rules(max_queue_depth: float,
                          max_request_p99_s: Optional[float] = None,
                          max_recompiles: float = 100.0) -> List[HealthRule]:
    """Defaults for a serving process; the dispatcher-liveness predicate
    is added by the server (it needs the engine object)."""
    rules = [
        HealthRule("queue_depth", "max_queue_depth", max_queue_depth),
        HealthRule("recompile_budget", "max_recompiles", max_recompiles),
    ]
    if max_request_p99_s is not None:
        rules.append(HealthRule(
            "request_p99", "max_step_p99", max_request_p99_s,
            metric="dl4j_serving_request_seconds"))
    return rules
