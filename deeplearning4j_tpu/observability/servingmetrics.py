"""Serving metric families — the one owner of their names/labels.

The serving engine, the HTTP front-end, and the bench all record through
this bundle so the families can never be declared twice with diverging
label sets (the registry raises on that).  Names continue the PR-1 set
(``dl4j_serving_requests_total`` etc.) and add the engine-era families:
bucket utilization (how much of each dispatched tile was real rows),
shed counter by reason (queue_full / deadline / shutdown), model swap
counter, and AOT warmup timings.
"""

from __future__ import annotations

import itertools

from deeplearning4j_tpu.observability.metrics import get_registry

_ENGINE_IDS = itertools.count()

_ROW_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
_UTIL_BUCKETS = (0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


class ServingMetrics:
    """All serving families, plus this engine's per-instance gauge
    children (labeled ``server=`` with a process-unique id so a second
    engine neither clobbers nor zeroes the first's gauges)."""

    def __init__(self, registry=None, server_id: str = None):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self.server_id = (server_id if server_id is not None
                          else f"s{next(_ENGINE_IDS)}")
        self.requests = reg.counter(
            "dl4j_serving_requests_total",
            "Predict requests by outcome", labels=("status",))
        self.latency = reg.histogram(
            "dl4j_serving_request_seconds",
            "End-to-end predict latency (enqueue -> response ready, "
            "including micro-batching wait)")
        self.queue_wait = reg.histogram(
            "dl4j_serving_queue_wait_seconds",
            "Time a request spent queued before its batch dispatched")
        self.request_rows = reg.histogram(
            "dl4j_serving_request_rows",
            "Rows per predict request", buckets=_ROW_BUCKETS)
        self.batch_rows = reg.histogram(
            "dl4j_serving_batch_rows",
            "Rows per dispatched micro-batch (padding excluded)",
            buckets=_ROW_BUCKETS)
        self.bucket_util = reg.histogram(
            "dl4j_serving_bucket_utilization",
            "Real rows / bucket rows per dispatched forward pass (1.0 = "
            "no padding FLOPs wasted)", buckets=_UTIL_BUCKETS)
        self.shed = reg.counter(
            "dl4j_serving_shed_total",
            "Requests shed by admission control, by reason",
            labels=("reason",))
        self.swaps = reg.counter(
            "dl4j_serving_model_swaps_total",
            "Completed model hot-swaps", labels=("model",))
        self.warmup_seconds = reg.histogram(
            "dl4j_serving_warmup_seconds",
            "Wall time of one model version's AOT bucket warmup")
        self.warmup_shapes = reg.gauge(
            "dl4j_serving_warmup_shapes",
            "Bucket shapes precompiled for the active version",
            labels=("model",))
        # per-instance children
        self.queue_depth = reg.gauge(
            "dl4j_serving_queue_depth",
            "Requests waiting for the micro-batch dispatcher",
            labels=("server",)).labels(server=self.server_id)
        self._max_batch_fam = reg.gauge(
            "dl4j_serving_max_batch",
            "Configured micro-batch row budget", labels=("server",))

    def set_max_batch(self, max_batch: int) -> None:
        self._max_batch_fam.set(max_batch, server=self.server_id)

    def bind_queue_depth(self, fn) -> None:
        """Live queue-depth gauge (the caller passes a weakref-safe
        callable so the registry never pins the engine)."""
        self.queue_depth.set_function(fn)

    def freeze_queue_depth(self) -> None:
        """Replace the live callback with 0 at engine stop (other engines'
        children are untouched)."""
        self.queue_depth.set(0.0)


_TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 10.0)

# inter-token latency sits an order of magnitude below TTFT (one decode
# step vs queue+prefill), so the buckets start at the dispatch floor
_ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5)


class GenerationMetrics:
    """Decode/continuous-batching families (``dl4j_decode_*``) — the one
    owner of their names/labels, same contract as ``ServingMetrics``.
    Per-instance gauges are labeled ``engine=`` with a process-unique id
    so a second generation engine neither clobbers nor zeroes the
    first's."""

    def __init__(self, registry=None, engine_id: str = None):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self.engine_id = (engine_id if engine_id is not None
                          else f"g{next(_ENGINE_IDS)}")
        self.requests = reg.counter(
            "dl4j_decode_requests_total",
            "Generation requests by terminal outcome (length/stop = "
            "completed; cancelled/deadline/shutdown/error = not)",
            labels=("status",))
        self.tokens = reg.counter(
            "dl4j_decode_tokens_total",
            "Tokens generated and delivered to request streams",
            labels=("model",))
        self.steps = reg.counter(
            "dl4j_decode_steps_total",
            "Decode-step dispatches (one per running-batch iteration)")
        self.prefix_pages = reg.counter(
            "dl4j_decode_prefix_pages_total",
            "Prompt pages at admission by outcome: shared counts pages an "
            "identical prefix let the request reference instead of "
            "prefilling fresh — BOTH in-flight sharing (another running "
            "request owns the page) and persistent prefix-cache hits "
            "(the radix tree kept it alive past its last request) land "
            "here; dl4j_prefix_cache_* tells the two apart",
            labels=("outcome",))
        # persistent radix-tree prefix cache (generation/prefix_cache.py)
        self.prefix_cache_hits = reg.counter(
            "dl4j_prefix_cache_hits",
            "Admissions whose prompt matched >= 1 cached radix-tree page "
            "(prefill priced at the suffix instead of the whole prompt)")
        self.prefix_cache_misses = reg.counter(
            "dl4j_prefix_cache_misses",
            "Admissions that matched nothing in the radix tree")
        self.prefix_cache_offloads = reg.counter(
            "dl4j_prefix_cache_offload_total",
            "Cold cached pages spilled device -> host tier (page slice "
            "copied out, device page freed, prefix still cached)")
        self.prefix_cache_restores = reg.counter(
            "dl4j_prefix_cache_restore_total",
            "Host-tier pages restored into fresh device pages on a hit")
        self.prefix_cache_evictions = reg.counter(
            "dl4j_prefix_cache_evictions_total",
            "Radix-tree nodes dropped outright, by reason (capacity = "
            "device room with no host budget left, host_capacity = host "
            "tier over budget, swap = weights changed, pool_reset = "
            "pools reseeded, abort = admission's prefill failed)",
            labels=("reason",))
        self.ttft = reg.histogram(
            "dl4j_decode_ttft_seconds",
            "Time to first token: submit -> first sampled token delivered "
            "(queue wait + prefill)", buckets=_TTFT_BUCKETS)
        self.inter_token = reg.histogram(
            "dl4j_decode_inter_token_seconds",
            "Inter-token latency: gap between consecutive delivered "
            "tokens of one request (the streaming-smoothness half of the "
            "decode SLO; TTFT is the other)", buckets=_ITL_BUCKETS)
        self.shed = reg.counter(
            "dl4j_decode_shed_total",
            "Generation requests shed by admission control, by reason",
            labels=("reason",))
        self.evictions = reg.counter(
            "dl4j_decode_evicted_total",
            "Requests removed from the RUNNING batch mid-flight (pages "
            "freed before completion), by reason",
            labels=("reason",))
        self.swaps = reg.counter(
            "dl4j_decode_model_swaps_total",
            "Completed generation-model hot-swaps", labels=("model",))
        # per-instance children
        self.active_slots = reg.gauge(
            "dl4j_decode_active_slots",
            "Requests currently holding a decode slot",
            labels=("engine",)).labels(engine=self.engine_id)
        self.page_util = reg.gauge(
            "dl4j_decode_page_utilization",
            "Allocated fraction of the paged KV pool (trash page "
            "excluded)", labels=("engine",)).labels(engine=self.engine_id)
        self.fused_attention = reg.gauge(
            "dl4j_decode_fused_attention",
            "1 when decode attention runs the fused paged kernel "
            "(helpers/paged_attention.py, pool + block table streamed "
            "through an online-softmax accumulator), 0 on the legacy "
            "gather+softmax oracle (DL4J_TPU_PAGED_GATHER=1 or helpers "
            "disabled)", labels=("engine",)).labels(engine=self.engine_id)
        self.prefix_cache_resident = reg.gauge(
            "dl4j_prefix_cache_resident_pages",
            "Device pages the prefix-cache radix tree currently keeps "
            "alive", labels=("engine",)).labels(engine=self.engine_id)
        self.prefix_cache_pinned = reg.gauge(
            "dl4j_prefix_cache_pinned_pages",
            "Cached pages protected by at least one session pin",
            labels=("engine",)).labels(engine=self.engine_id)
        self.prefix_cache_host_bytes = reg.gauge(
            "dl4j_prefix_cache_host_tier_bytes",
            "Bytes of offloaded KV page payloads held in the host-RAM "
            "tier", labels=("engine",)).labels(engine=self.engine_id)
        self.batch_occupancy = reg.histogram(
            "dl4j_decode_batch_occupancy",
            "Active slots per dispatched decode step / total slots (1.0 = "
            "every lane did useful work)",
            buckets=_UTIL_BUCKETS)
