"""Recompile detection for jitted step functions.

On TPU the silent throughput killer is XLA retracing/recompilation from
shape churn — a ragged final batch, a TBPTT tail window, a mask appearing
mid-run — each costing seconds of compile against a millisecond step.
Nothing in the reference detects this (it has no compiler in the loop).

``instrument(jax.jit(step), "name")`` wraps the jitted callable: every call
fingerprints the *abstract* signature of the inputs (pytree structure +
shape/dtype/sharding per leaf — the things jit keys its cache on), counts
distinct signatures as compiles in the metrics registry, and logs ONE
warning per *new* signature after the first with the old→new delta, e.g.::

    recompile #2 of MultiLayerNetwork.train_step: args[4]:
    f32[128,784] -> f32[96,784]

The fingerprint is a few microseconds of host work per call (tuple of
shape/dtype ids per leaf); the paths needed for a readable delta are only
computed on a miss.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability import profiling, shardstats

logger = logging.getLogger("deeplearning4j_tpu.observability")

_COMPILES = "dl4j_compiles_total"
_RECOMPILES = "dl4j_recompiles_total"


def _leaf_sig(leaf: Any) -> Tuple:
    """Abstract signature of one pytree leaf: what jit keys its cache on.
    The sharding is kept as the OBJECT (hashable, cheap) — stringifying it
    per call was the dominant fingerprint cost on large pytrees."""
    shape = getattr(leaf, "shape", None)
    if shape is None:
        # non-array static-ish leaf (python scalar, string…): jit treats
        # python numbers as weak-typed 0-d arrays; keep the type
        return (type(leaf).__name__,)
    dtype = getattr(leaf, "dtype", None)
    return (tuple(shape), str(dtype), getattr(leaf, "sharding", None))


def _fmt_leaf_sig(sig: Tuple) -> str:
    if len(sig) == 1:
        return sig[0]
    shape, dtype, sharding = sig
    short = {"float32": "f32", "float64": "f64", "bfloat16": "bf16",
             "int32": "i32", "int64": "i64", "bool": "b1",
             "uint32": "u32"}.get(dtype, dtype)
    s = f"{short}[{','.join(str(d) for d in shape)}]"
    sh = "" if sharding is None else repr(sharding)
    if sh and "SingleDevice" not in sh:
        s += f"@{sh}"
    return s


def fingerprint(args: Tuple, kwargs: Dict) -> Tuple:
    """Hashable abstract signature of a call's inputs."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_sig(l) for l in leaves))


def _fmt_signature(sig: Tuple, max_leaves: int = 12) -> str:
    """Readable one-line form of a fingerprint's leaf signatures
    (``f32[128,784], f32[128,10], …``) for flight-recorder records."""
    _treedef, leaves = sig
    parts = [_fmt_leaf_sig(s) for s in leaves[:max_leaves]]
    if len(leaves) > max_leaves:
        parts.append(f"… {len(leaves) - max_leaves} more")
    return ", ".join(parts)


def _leaf_paths(args: Tuple, kwargs: Dict) -> List[str]:
    """Human-readable path per leaf, same order as ``fingerprint``."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path((args, kwargs))[0]
    out = []
    for path, _ in flat:
        label = jax.tree_util.keystr(path)
        # keystr renders "(0,)[4]['w']" style; trim the (args, kwargs) root
        if label.startswith("[0]"):
            label = "args" + label[3:]
        elif label.startswith("[1]"):
            label = "kwargs" + label[3:]
        out.append(label)
    return out


class RecompileDetector:
    """Tracks abstract input signatures of one jitted function."""

    def __init__(self, name: str, registry=None,
                 warn: Optional[Callable[[str], None]] = None):
        from deeplearning4j_tpu.observability.metrics import get_registry

        self.name = name
        self.warn = warn or logger.warning
        self._lock = threading.Lock()
        self._seen: Dict[Tuple, int] = {}   # signature -> compile ordinal
        self._last: Optional[Tuple] = None
        self.compile_count = 0
        self.recompile_count = 0  # new signatures after the first
        # signature -> XLA cost analysis (filled when a profiler is
        # installed; see check(cost_fn=)).  last_cost is the CURRENT
        # signature's entry — _InstrumentedJit reads it right after
        # check() to attribute the dispatch's FLOPs to the step.
        self._cost_by_sig: Dict[Tuple, Dict] = {}
        self.last_cost: Optional[Dict] = None
        reg = registry if registry is not None else get_registry()
        self._m_compiles = compile_counter(name, reg)
        self._m_recompiles = reg.counter(
            _RECOMPILES, "Signature changes after the first compile "
            "(shape/dtype/sharding churn)", labels=("fn",)
        ).labels(fn=name)

    def check(self, args: Any, kwargs: Dict, expected: bool = False,
              cost_fn: Optional[Callable[[], Dict]] = None) -> bool:
        """Record this call's signature (``args`` is any pytree — a tuple
        of positional args, or a position-keyed dict when the wrapper
        subsets by ``argnums``); returns True when it is new (i.e. this
        call compiles).  ``expected=True`` marks a PLANNED compile (e.g.
        serving AOT warmup sweeping its bucket shapes): it still counts
        in ``dl4j_compiles_total`` but does not warn or count as a
        recompile — those alert only on unplanned signature churn.

        ``cost_fn`` (profiler seam): called once per NEW signature to
        fetch its XLA cost analysis; the result is cached per signature,
        exposed as ``last_cost`` for every later call with that
        signature, and an UNEXPECTED recompile dumps the new abstract
        signature with its flops/bytes delta vs the evicted one into the
        flight recorder — not just a counter bump."""
        sig = fingerprint(args, kwargs)
        with self._lock:
            known = sig in self._seen
            if not known:
                self.compile_count += 1
                self._seen[sig] = self.compile_count
                self._m_compiles.inc()
            prev, self._last = self._last, sig
            if known:
                self.last_cost = self._cost_by_sig.get(sig)
                return False
        # cost analysis OUTSIDE the lock: it lowers + compiles
        cost: Optional[Dict] = None
        if cost_fn is not None:
            try:
                cost = cost_fn() or {}
            except Exception:
                cost = {}
            with self._lock:
                self._cost_by_sig[sig] = cost
        # dl4jlint: disable-next-line=lock-discipline -- GIL-atomic reference publish; readers are monitoring-grade and tolerate the brief pre-cost window
        self.last_cost = cost
        # compiles land in the flight record too: "what happened right
        # before the hang" is usually a compile or a shape change
        from deeplearning4j_tpu.observability.flightrecorder import (
            get_flight_recorder,
        )

        get_flight_recorder().record(
            # dl4jlint: disable-next-line=lock-discipline -- reads back the ordinal this same call just assigned under the lock; a concurrent compile only skews the label
            "compile", fn=self.name, ordinal=self.compile_count,
            expected=bool(expected))
        if prev is not None and not expected:
            self.recompile_count += 1
            self._m_recompiles.inc()
            msg = self._delta_message(prev, sig, args, kwargs)
            self.warn(msg)
            self._record_recompile_event(prev, sig, cost)
        return True

    def _record_recompile_event(self, prev: Tuple, new: Tuple,
                                cost: Optional[Dict]) -> None:
        """The satellite-grade recompile record: new abstract signature +
        cost-analysis summary (flops/bytes delta vs the evicted
        signature) into the flight recorder.  Cost fields appear when a
        profiler had analysis enabled for both signatures."""
        from deeplearning4j_tpu.observability.flightrecorder import (
            get_flight_recorder,
        )

        ev: Dict[str, Any] = {
            # dl4jlint: disable-next-line=lock-discipline -- flight-record label read; exactness not load-bearing
            "fn": self.name, "ordinal": self.compile_count,
            "signature": _fmt_signature(new),
            "evicted_signature": _fmt_signature(prev),
        }
        with self._lock:
            prev_cost = self._cost_by_sig.get(prev)
        if cost:
            ev["flops"] = cost.get("flops")
            ev["bytes_accessed"] = cost.get("bytes_accessed")
        if prev_cost:
            ev["evicted_flops"] = prev_cost.get("flops")
            ev["evicted_bytes_accessed"] = prev_cost.get("bytes_accessed")
        if cost and prev_cost:
            ev["flops_delta"] = ((cost.get("flops") or 0.0)
                                 - (prev_cost.get("flops") or 0.0))
            ev["bytes_delta"] = ((cost.get("bytes_accessed") or 0.0)
                                 - (prev_cost.get("bytes_accessed") or 0.0))
        get_flight_recorder().record("recompile", **ev)

    def _delta_message(self, old: Tuple, new: Tuple, args, kwargs) -> str:
        old_def, old_leaves = old
        new_def, new_leaves = new
        parts: List[str] = []
        if old_def != new_def:
            parts.append("pytree structure changed")
        if len(old_leaves) == len(new_leaves):
            try:
                paths = _leaf_paths(args, kwargs)
            except Exception:
                paths = [f"leaf[{i}]" for i in range(len(new_leaves))]
            for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
                if o != n:
                    parts.append(f"{paths[i]}: {_fmt_leaf_sig(o)} -> "
                                 f"{_fmt_leaf_sig(n)}")
        else:  # e.g. a mask appearing mid-run (None -> array)
            parts.append(f"leaf count {len(old_leaves)} -> "
                         f"{len(new_leaves)}")
        delta = "; ".join(parts[:8]) or "signature changed"
        if len(parts) > 8:
            delta += f"; … {len(parts) - 8} more"
        # dl4jlint: disable-next-line=lock-discipline -- warning-text label read; exactness not load-bearing
        return (f"recompile #{self.compile_count} of {self.name}: {delta} "
                f"(each new signature costs an XLA compilation; pad/bucket "
                f"inputs to stable shapes to avoid this)")


class _InstrumentedJit:
    """Transparent wrapper: ``__call__`` runs the detector then the jitted
    fn; everything else (``lower``, ``trace``, ``clear_cache``…) delegates,
    so AOT-compile workflows (bench.py) keep working on the wrapped
    object.

    ``argnums`` restricts the fingerprint to those positional args — the
    fit loops pass only the DATA argument positions (batch, labels, masks,
    carries), because the params/optimizer-state pytrees cannot change
    abstract shape between steps (each step's inputs are the previous
    step's outputs) and fingerprinting hundreds of param leaves every
    iteration is measurable host overhead.

    Profiler seam: while a ``StepProfiler`` with cost analysis is
    installed, each NEW signature is cost-analyzed (abstract lowering of
    the FULL argument list — safe with donation, nothing executes) and
    every call reports its signature's cached flops/bytes to the profiler
    (``note_dispatch``), which rolls them into the step's MFU/roofline
    gauges at the ``step_guard`` boundary."""

    __slots__ = ("_fn", "detector", "_argnums")

    def __init__(self, fn: Callable, detector: RecompileDetector,
                 argnums: Optional[Tuple[int, ...]] = None):
        self._fn = fn
        self.detector = detector
        self._argnums = argnums

    def __call__(self, *args, **kwargs):
        prof = profiling.active_profiler()
        coll = shardstats.active_collector()
        cost_fn = None
        fn = self._fn
        if coll is not None:
            # superset analysis: memory_analysis + collective census +
            # the same flops/bytes fields jit_cost_analysis returns, from
            # ONE lower+compile — an installed profiler reads it as-is
            cost_fn = lambda: shardstats.program_analysis(fn, args, kwargs)
        elif prof is not None and prof.cost_analysis:
            cost_fn = lambda: profiling.jit_cost_analysis(fn, args, kwargs)
        if self._argnums is None:
            self.detector.check(args, kwargs, cost_fn=cost_fn)
        else:
            # dict keyed by the ORIGINAL position so delta paths stay
            # meaningful ("args[4]: f32[32,8] -> f32[20,8]")
            sel = {i: args[i] for i in self._argnums if i < len(args)}
            self.detector.check(sel, kwargs, cost_fn=cost_fn)
        if prof is not None:
            prof.note_dispatch(self.detector.name, self.detector.last_cost)
        if coll is not None:
            coll.note_dispatch(self.detector.name, self.detector.last_cost)
        return self._fn(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def __repr__(self):
        return f"InstrumentedJit({self.detector.name})"


def instrument(fn: Callable, name: str, registry=None,
               warn: Optional[Callable[[str], None]] = None,
               argnums: Optional[Tuple[int, ...]] = None) -> _InstrumentedJit:
    """Wrap a jitted callable with a RecompileDetector (see module doc).
    ``argnums``: fingerprint only these positional args (hot-loop cost
    control; see ``_InstrumentedJit``)."""
    return _InstrumentedJit(fn, RecompileDetector(name, registry, warn),
                            None if argnums is None else tuple(argnums))


def compile_counter(fn_name: str, registry=None):
    """The shared ``dl4j_compiles_total{fn=}`` child for callers outside
    the detector (e.g. bench AOT compiles) — ONE owner for the family
    declaration, so label sets can never diverge."""
    from deeplearning4j_tpu.observability.metrics import get_registry

    reg = registry if registry is not None else get_registry()
    return reg.counter(
        _COMPILES, "Distinct abstract input signatures (≈ XLA "
        "compilations) per jitted function", labels=("fn",)).labels(
        fn=fn_name)
