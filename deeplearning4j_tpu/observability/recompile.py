"""Recompile detection for jitted step functions.

On TPU the silent throughput killer is XLA retracing/recompilation from
shape churn — a ragged final batch, a TBPTT tail window, a mask appearing
mid-run — each costing seconds of compile against a millisecond step.
Nothing in the reference detects this (it has no compiler in the loop).

``instrument(jax.jit(step), "name")`` wraps the jitted callable: every call
fingerprints the *abstract* signature of the inputs (pytree structure +
shape/dtype/sharding per leaf — the things jit keys its cache on), counts
distinct signatures as compiles in the metrics registry, and logs ONE
warning per *new* signature after the first with the old→new delta, e.g.::

    recompile #2 of MultiLayerNetwork.train_step: args[4]:
    f32[128,784] -> f32[96,784]

The fingerprint is a few microseconds of host work per call (tuple of
shape/dtype ids per leaf); the paths needed for a readable delta are only
computed on a miss.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu.observability")

_COMPILES = "dl4j_compiles_total"
_RECOMPILES = "dl4j_recompiles_total"


def _leaf_sig(leaf: Any) -> Tuple:
    """Abstract signature of one pytree leaf: what jit keys its cache on.
    The sharding is kept as the OBJECT (hashable, cheap) — stringifying it
    per call was the dominant fingerprint cost on large pytrees."""
    shape = getattr(leaf, "shape", None)
    if shape is None:
        # non-array static-ish leaf (python scalar, string…): jit treats
        # python numbers as weak-typed 0-d arrays; keep the type
        return (type(leaf).__name__,)
    dtype = getattr(leaf, "dtype", None)
    return (tuple(shape), str(dtype), getattr(leaf, "sharding", None))


def _fmt_leaf_sig(sig: Tuple) -> str:
    if len(sig) == 1:
        return sig[0]
    shape, dtype, sharding = sig
    short = {"float32": "f32", "float64": "f64", "bfloat16": "bf16",
             "int32": "i32", "int64": "i64", "bool": "b1",
             "uint32": "u32"}.get(dtype, dtype)
    s = f"{short}[{','.join(str(d) for d in shape)}]"
    sh = "" if sharding is None else repr(sharding)
    if sh and "SingleDevice" not in sh:
        s += f"@{sh}"
    return s


def fingerprint(args: Tuple, kwargs: Dict) -> Tuple:
    """Hashable abstract signature of a call's inputs."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_sig(l) for l in leaves))


def _leaf_paths(args: Tuple, kwargs: Dict) -> List[str]:
    """Human-readable path per leaf, same order as ``fingerprint``."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path((args, kwargs))[0]
    out = []
    for path, _ in flat:
        label = jax.tree_util.keystr(path)
        # keystr renders "(0,)[4]['w']" style; trim the (args, kwargs) root
        if label.startswith("[0]"):
            label = "args" + label[3:]
        elif label.startswith("[1]"):
            label = "kwargs" + label[3:]
        out.append(label)
    return out


class RecompileDetector:
    """Tracks abstract input signatures of one jitted function."""

    def __init__(self, name: str, registry=None,
                 warn: Optional[Callable[[str], None]] = None):
        from deeplearning4j_tpu.observability.metrics import get_registry

        self.name = name
        self.warn = warn or logger.warning
        self._lock = threading.Lock()
        self._seen: Dict[Tuple, int] = {}   # signature -> compile ordinal
        self._last: Optional[Tuple] = None
        self.compile_count = 0
        self.recompile_count = 0  # new signatures after the first
        reg = registry if registry is not None else get_registry()
        self._m_compiles = compile_counter(name, reg)
        self._m_recompiles = reg.counter(
            _RECOMPILES, "Signature changes after the first compile "
            "(shape/dtype/sharding churn)", labels=("fn",)
        ).labels(fn=name)

    def check(self, args: Any, kwargs: Dict, expected: bool = False) -> bool:
        """Record this call's signature (``args`` is any pytree — a tuple
        of positional args, or a position-keyed dict when the wrapper
        subsets by ``argnums``); returns True when it is new (i.e. this
        call compiles).  ``expected=True`` marks a PLANNED compile (e.g.
        serving AOT warmup sweeping its bucket shapes): it still counts
        in ``dl4j_compiles_total`` but does not warn or count as a
        recompile — those alert only on unplanned signature churn."""
        sig = fingerprint(args, kwargs)
        with self._lock:
            known = sig in self._seen
            if not known:
                self.compile_count += 1
                self._seen[sig] = self.compile_count
                self._m_compiles.inc()
            prev, self._last = self._last, sig
        if known:
            return False
        # compiles land in the flight record too: "what happened right
        # before the hang" is usually a compile or a shape change
        from deeplearning4j_tpu.observability.flightrecorder import (
            get_flight_recorder,
        )

        get_flight_recorder().record(
            "compile", fn=self.name, ordinal=self.compile_count,
            expected=bool(expected))
        if prev is not None and not expected:
            self.recompile_count += 1
            self._m_recompiles.inc()
            self.warn(self._delta_message(prev, sig, args, kwargs))
        return True

    def _delta_message(self, old: Tuple, new: Tuple, args, kwargs) -> str:
        old_def, old_leaves = old
        new_def, new_leaves = new
        parts: List[str] = []
        if old_def != new_def:
            parts.append("pytree structure changed")
        if len(old_leaves) == len(new_leaves):
            try:
                paths = _leaf_paths(args, kwargs)
            except Exception:
                paths = [f"leaf[{i}]" for i in range(len(new_leaves))]
            for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
                if o != n:
                    parts.append(f"{paths[i]}: {_fmt_leaf_sig(o)} -> "
                                 f"{_fmt_leaf_sig(n)}")
        else:  # e.g. a mask appearing mid-run (None -> array)
            parts.append(f"leaf count {len(old_leaves)} -> "
                         f"{len(new_leaves)}")
        delta = "; ".join(parts[:8]) or "signature changed"
        if len(parts) > 8:
            delta += f"; … {len(parts) - 8} more"
        return (f"recompile #{self.compile_count} of {self.name}: {delta} "
                f"(each new signature costs an XLA compilation; pad/bucket "
                f"inputs to stable shapes to avoid this)")


class _InstrumentedJit:
    """Transparent wrapper: ``__call__`` runs the detector then the jitted
    fn; everything else (``lower``, ``trace``, ``clear_cache``…) delegates,
    so AOT-compile workflows (bench.py) keep working on the wrapped
    object.

    ``argnums`` restricts the fingerprint to those positional args — the
    fit loops pass only the DATA argument positions (batch, labels, masks,
    carries), because the params/optimizer-state pytrees cannot change
    abstract shape between steps (each step's inputs are the previous
    step's outputs) and fingerprinting hundreds of param leaves every
    iteration is measurable host overhead."""

    __slots__ = ("_fn", "detector", "_argnums")

    def __init__(self, fn: Callable, detector: RecompileDetector,
                 argnums: Optional[Tuple[int, ...]] = None):
        self._fn = fn
        self.detector = detector
        self._argnums = argnums

    def __call__(self, *args, **kwargs):
        if self._argnums is None:
            self.detector.check(args, kwargs)
        else:
            # dict keyed by the ORIGINAL position so delta paths stay
            # meaningful ("args[4]: f32[32,8] -> f32[20,8]")
            sel = {i: args[i] for i in self._argnums if i < len(args)}
            self.detector.check(sel, kwargs)
        return self._fn(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def __repr__(self):
        return f"InstrumentedJit({self.detector.name})"


def instrument(fn: Callable, name: str, registry=None,
               warn: Optional[Callable[[str], None]] = None,
               argnums: Optional[Tuple[int, ...]] = None) -> _InstrumentedJit:
    """Wrap a jitted callable with a RecompileDetector (see module doc).
    ``argnums``: fingerprint only these positional args (hot-loop cost
    control; see ``_InstrumentedJit``)."""
    return _InstrumentedJit(fn, RecompileDetector(name, registry, warn),
                            None if argnums is None else tuple(argnums))


def compile_counter(fn_name: str, registry=None):
    """The shared ``dl4j_compiles_total{fn=}`` child for callers outside
    the detector (e.g. bench AOT compiles) — ONE owner for the family
    declaration, so label sets can never diverge."""
    from deeplearning4j_tpu.observability.metrics import get_registry

    reg = registry if registry is not None else get_registry()
    return reg.counter(
        _COMPILES, "Distinct abstract input signatures (≈ XLA "
        "compilations) per jitted function", labels=("fn",)).labels(
        fn=fn_name)
