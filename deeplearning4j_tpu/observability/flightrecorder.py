"""Flight recorder + step watchdog: the crash/hang diagnosis layer.

When an 8-worker training run (or a serving dispatcher) stops making
progress, a Prometheus scrape tells you *that* it is stuck, not *where*.
The reference stack leaned on Spark's driver UI for that; here the
equivalent is a **flight recorder** — a bounded ring buffer of structured
events (step begin/end, compile, model swap, shed, checkpoint) that the
fit loops, the training masters, and the serving engine feed as they run —
plus a **step watchdog**: a daemon thread that notices an armed step or
dispatch exceeding its deadline and dumps everything a human needs to
diagnose the hang into one JSONL report:

- the flight record (the last N structured events, newest last),
- the live span stack of every thread (what each thread is *inside of*
  right now — ``SpanTracer.live_spans``),
- a registry snapshot (every metric family as JSON),
- PJRT device-memory stats (HBM pressure is the classic TPU hang cause).

The same report is produced on a fit-loop exception (``crash_dump``), so a
crashed run leaves the identical artifact a hung run would.  Reading a
dump: docs/observability.md ("reading a flight-recorder dump").

Hot-loop cost: ``record()`` is one lock + deque append; ``step_guard`` adds
two of those plus a dict store when a watchdog is armed.  Nothing here ever
forces a device->host sync.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_DUMPS = "dl4j_watchdog_dumps_total"


class FlightEvent:
    """One structured event: wall-clock + monotonic timestamps, a kind
    (``step_begin``/``step_end``/``step_error``/``compile``/``swap``/
    ``shed``/``checkpoint``/...), and free-form attrs."""

    __slots__ = ("ts", "mono_ns", "kind", "attrs")

    def __init__(self, kind: str, attrs: Dict[str, Any]):
        self.ts = time.time()
        self.mono_ns = time.perf_counter_ns()
        self.kind = kind
        self.attrs = attrs

    def to_dict(self) -> Dict[str, Any]:
        return {"ts": self.ts, "mono_ns": self.mono_ns, "kind": self.kind,
                **self.attrs}


class FlightRecorder:
    """Bounded ring buffer of recent ``FlightEvent``s (O(1) memory however
    long the run; ``dropped`` counts evictions)."""

    def __init__(self, capacity: int = 2048):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self.dropped = 0

    def record(self, kind: str, **attrs) -> None:
        ev = FlightEvent(kind, attrs)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def events(self) -> List[FlightEvent]:
        with self._lock:
            return list(self._events)

    def to_list(self) -> List[Dict[str, Any]]:
        return [e.to_dict() for e in self.events()]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0


_global_lock = threading.Lock()
_global_recorder: Optional[FlightRecorder] = None


def get_flight_recorder() -> FlightRecorder:
    """The process-wide default recorder (created on first use)."""
    global _global_recorder
    rec = _global_recorder
    if rec is not None:
        return rec
    with _global_lock:
        if _global_recorder is None:
            _global_recorder = FlightRecorder()
        return _global_recorder


def set_flight_recorder(rec: Optional[FlightRecorder]) -> FlightRecorder:
    """Swap the process-wide recorder (tests); returns the new one."""
    global _global_recorder
    with _global_lock:
        _global_recorder = rec or FlightRecorder()
        return _global_recorder


# --------------------------------------------------------------- dump report
def dump_flight_report(path: str, reason: str, *, recorder=None, tracer=None,
                       registry=None, context: Optional[Dict] = None) -> str:
    """Write the full diagnosis artifact as JSON lines (one record per
    line; the ``record`` field says which kind).  Every section is
    best-effort — a broken backend must not prevent the rest of the dump."""
    from deeplearning4j_tpu.observability.metrics import get_registry
    from deeplearning4j_tpu.observability.tracing import get_tracer

    rec = recorder if recorder is not None else get_flight_recorder()
    tr = tracer if tracer is not None else get_tracer()
    reg = registry if registry is not None else get_registry()
    lines: List[Dict[str, Any]] = [{
        "record": "meta", "reason": reason, "time": time.time(),
        "pid": os.getpid(), "context": context or {},
        "events_dropped": rec.dropped,
    }]
    for ev in rec.events():
        lines.append({"record": "event", **ev.to_dict()})
    try:
        for span in tr.live_spans():
            lines.append({"record": "live_span", **span})
    except Exception as e:
        lines.append({"record": "error", "section": "live_spans",
                      "error": repr(e)})
    try:
        lines.append({"record": "registry", "metrics": reg.to_json()})
    except Exception as e:
        lines.append({"record": "error", "section": "registry",
                      "error": repr(e)})
    try:
        from deeplearning4j_tpu.observability.memory import device_memory_stats

        lines.append({"record": "device_memory",
                      "devices": device_memory_stats()})
    except Exception as e:
        lines.append({"record": "error", "section": "device_memory",
                      "error": repr(e)})
    try:
        # WHAT holds the memory: live buffers by shape/dtype plus the
        # per-leaf breakdown of any profiler-tracked model
        from deeplearning4j_tpu.observability import profiling

        lines.append({"record": "memory_attribution",
                      **profiling.memory_attribution()})
    except Exception as e:
        lines.append({"record": "error", "section": "memory_attribution",
                      "error": repr(e)})
    try:
        # WHERE the bytes live: the most recent sharding ledger per
        # component (per-device bytes, replication factors, ZeRO
        # projection) so an OOM-adjacent hang dump carries per-tree
        # byte attribution in the post-mortem
        from deeplearning4j_tpu.observability import shardstats

        lines.append({"record": "sharding_ledger",
                      "ledgers": shardstats.latest_ledgers()})
    except Exception as e:
        lines.append({"record": "error", "section": "sharding_ledger",
                      "error": repr(e)})
    with open(path, "w") as f:
        for obj in lines:
            f.write(json.dumps(obj, default=str) + "\n")
    return path


def read_flight_report(path: str) -> List[Dict[str, Any]]:
    """Parse a report back into its records (runbook/test helper)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class StepWatchdog:
    """Daemon thread watching armed steps/dispatches against a deadline.

    Usage::

        wd = StepWatchdog(deadline_s=120.0, report_dir="diag").install()
        # fit loops / serving automatically arm via step_guard(); a step
        # exceeding its deadline dumps flight-<reason>-<pid>-<n>.jsonl
        ...
        wd.uninstall()

    One dump per hung step (re-armed steps dump again); a completed step
    disarms itself.  ``dump()`` is public so crash paths (fit-loop
    exceptions) produce the identical artifact.
    """

    def __init__(self, deadline_s: float = 60.0, report_dir: str = ".",
                 poll_interval_s: Optional[float] = None, recorder=None,
                 tracer=None, registry=None):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.report_dir = str(report_dir)
        self.poll_interval_s = (poll_interval_s if poll_interval_s is not None
                                else max(0.05, min(1.0, deadline_s / 4.0)))
        self._recorder = recorder
        self._tracer = tracer
        self._registry = registry
        self._lock = threading.Lock()
        self._armed: Dict[int, Dict[str, Any]] = {}
        self._tokens = itertools.count(1)
        self._seq = itertools.count(1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.dumps: List[str] = []          # report paths, oldest first

    # ------------------------------------------------------------ arm/disarm
    def arm(self, name: str, deadline_s: Optional[float] = None,
            **attrs) -> int:
        token = next(self._tokens)
        entry = {
            "name": name, "attrs": attrs,
            "armed_at": time.monotonic(),
            "deadline": time.monotonic() + (deadline_s or self.deadline_s),
            "thread": threading.current_thread().name,
            "dumped": False,
        }
        with self._lock:
            self._armed[token] = entry
        return token

    def disarm(self, token: int) -> None:
        with self._lock:
            self._armed.pop(token, None)

    @contextmanager
    def watch(self, name: str, deadline_s: Optional[float] = None, **attrs):
        token = self.arm(name, deadline_s, **attrs)
        try:
            yield
        finally:
            self.disarm(token)

    # --------------------------------------------------------------- thread
    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            now = time.monotonic()
            overdue = []
            with self._lock:
                for entry in self._armed.values():
                    if not entry["dumped"] and now > entry["deadline"]:
                        entry["dumped"] = True
                        overdue.append(entry)
            for entry in overdue:
                try:
                    self.dump("hang", step=entry["name"],
                              thread=entry["thread"],
                              overdue_s=round(now - entry["deadline"], 3),
                              armed_s=round(now - entry["armed_at"], 3),
                              **entry["attrs"])
                except Exception:
                    pass   # a failing dump must not kill the watchdog

    def dump(self, reason: str, **context) -> str:
        """Write one report now (used by the poll loop and by crash
        paths); returns the report path."""
        from deeplearning4j_tpu.observability.metrics import get_registry

        os.makedirs(self.report_dir, exist_ok=True)
        path = os.path.join(
            self.report_dir,
            f"flight-{reason}-{os.getpid()}-{next(self._seq)}.jsonl")
        dump_flight_report(path, reason, recorder=self._recorder,
                           tracer=self._tracer, registry=self._registry,
                           context=context)
        reg = (self._registry if self._registry is not None
               else get_registry())
        reg.counter(
            _DUMPS, "Flight-recorder reports written by the step watchdog "
            "(hang) and crash paths (exception)", labels=("reason",)
        ).inc(reason=reason)
        self.dumps.append(path)
        try:
            # capture-on-watchdog: arm the installed profiler so the next
            # step that runs after this dump gets a full trace capture
            from deeplearning4j_tpu.observability import profiling

            profiling.notify_watchdog(reason)
        except Exception:
            pass
        return path

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "StepWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="dl4j-step-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def install(self) -> "StepWatchdog":
        """Start and make this the process-wide watchdog that
        ``step_guard`` arms automatically."""
        global _active_watchdog
        self.start()
        _active_watchdog = self
        return self

    def uninstall(self) -> None:
        global _active_watchdog
        if _active_watchdog is self:
            _active_watchdog = None
        self.stop()


_active_watchdog: Optional[StepWatchdog] = None


def get_watchdog() -> Optional[StepWatchdog]:
    """The installed watchdog, or None (reads are lock-free: assignment of
    a module global is atomic)."""
    return _active_watchdog


# ------------------------------------------------------------- integration
@contextmanager
def step_guard(name: str, **attrs):
    """The one hook fit loops, masters, and the serving dispatcher wrap
    their step/dispatch in: records ``step_begin``/``step_end`` (or
    ``step_error``) flight events, arms the installed watchdog for the
    duration, and — when a ``StepProfiler`` is installed — opens the
    per-step attribution frame that turns dispatched FLOPs into
    MFU/roofline gauges (and trace captures on trigger).  Dump-on-
    exception lives in ``crash_dump`` (called once at the fit-loop level)
    so a failing step is recorded here but reported exactly once there."""
    rec = get_flight_recorder()
    rec.record("step_begin", name=name, **attrs)
    wd = _active_watchdog
    token = wd.arm(name, **attrs) if wd is not None else None
    prof = frame = None
    try:
        from deeplearning4j_tpu.observability import profiling

        prof = profiling.active_profiler()
        if prof is not None:
            frame = prof.on_step_begin(name, attrs)
    except Exception:   # a broken profiler must never break training
        prof = frame = None
    t0 = time.perf_counter()
    err = None
    try:
        yield
    except BaseException as e:
        err = e
        rec.record("step_error", name=name, error=repr(e), **attrs)
        raise
    else:
        rec.record("step_end", name=name,
                   seconds=round(time.perf_counter() - t0, 6), **attrs)
    finally:
        if prof is not None and frame is not None:
            try:
                prof.on_step_end(name, time.perf_counter() - t0, attrs,
                                 frame, error=err)
            except Exception:
                pass
        if wd is not None:
            wd.disarm(token)


def crash_dump(reason: str, **context) -> Optional[str]:
    """Record a ``crash`` flight event and, when a watchdog is installed,
    write the same JSONL report a hang would produce.  Returns the report
    path (None when no watchdog is installed — there is nowhere configured
    to write to)."""
    get_flight_recorder().record("crash", reason=reason, **context)
    wd = _active_watchdog
    if wd is None:
        return None
    try:
        return wd.dump(reason, **context)
    except Exception:
        return None
