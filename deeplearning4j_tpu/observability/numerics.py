"""Numerics observability: the precision ledger.

ROADMAP items 1 (fused kernels) and 3 (bf16/fp8 mixed precision) both
stall on the same blind spot: the repo cannot *measure* its numerics.
This module is the measurement substrate — per-layer dynamic-range
statistics (max-abs, exponent histogram, fraction of values that would
underflow or overflow each candidate narrow format) for gradients,
updater moments, and activations, computed INSIDE the jitted train step
of both facades using the introspection pattern (PR 12):

- **device-side collection** (jit-safe half): one fused reduction pass
  per leaf packs everything into ONE flat ``[N]`` f32 vector carried in
  a reserved ``__numerics__`` subtree of the updater-state pytree — so
  it stacks per replica in ``ParallelWrapper``, replicates in
  ``SyncTrainingMaster``, donates with the step, and checkpoints with
  the Adam moments.  Zero host syncs on non-report steps, zero
  recompiles after the first step, and a net with ``conf.numerics``
  unset keeps the exact pre-ledger trace (bit-identical healthy path);
- **harvest** (host half): ONE batched device->host transfer per
  reporting interval fans the vector out into per-(component, layer)
  entries with a **safety verdict** per candidate format —
  ``format_precision_ledger`` renders the operator view, the
  ``dl4j_layer_overflow_risk{component,layer,dtype}`` gauges mirror it,
  and ``GET /train/numerics`` serves it from the UI server;
- **loss-scale telemetry joins the ledger**: the step's live
  ``__stability__`` loss scale is stamped into the packed vector, so a
  harvested report always shows which scale the gradient statistics
  were measured under (gradient stats are unscaled exactly, like the
  introspection norms);
- ``kv_page_ledger``: per-page dynamic-range stats over the generation
  engine's ``PagedKVCache`` pools — the int8-KV quantization-readiness
  evidence for ROADMAP item 3.

Candidate formats and what "risky" means (docs/observability.md
"Numerics" has the full definitions):

- **overflow**: fraction of values with ``|x|`` above the format's max
  finite value — any nonzero fraction is an instant red flag;
- **underflow**: fraction of NONZERO values below the format's min
  normal — they flush to zero (or denormals) when narrowed;
- **absorption**: fraction of nonzero values more than the format's
  mantissa width below the tensor's max exponent — at the tensor's own
  scale these contribute nothing to an accumulation in that format.
  This is the bf16 failure mode: bf16 shares f32's exponent range, so
  it almost never over/underflows — it *absorbs*.  A gradient spike
  (``FaultInjector.poison_gradients(mode="spike")``) raises the max
  exponent by ~13 bits and flips the verdict, which is exactly the
  drill ``tests/test_numerics.py`` runs.

Metric families (docs/observability.md): ``dl4j_layer_overflow_risk``,
``dl4j_layer_max_abs``.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Reserved subtree of the updater-state pytree (the ``__stability__`` /
# ``__introspect__`` pattern: stacked per replica, replicated by the
# sync master, donated, checkpointed without extra plumbing).
STATE_KEY = "__numerics__"

_RISK = "dl4j_layer_overflow_risk"
_MAXABS = "dl4j_layer_max_abs"

logger = logging.getLogger("deeplearning4j_tpu.observability")

# Candidate narrow formats, in packed-vector order.  (name, min normal,
# max finite).  int8 is the per-page-scale variant the paged KV cache
# would use: scale = max_abs / 127, so a value quantizes to zero when
# |x| < max_abs / 254 — its "min normal" is relative to the tensor's
# own max, folded into the stats pass instead of a static threshold.
FORMATS: Tuple[Tuple[str, float, float], ...] = (
    ("bfloat16", 2.0 ** -126, 3.3895313892515355e38),
    ("float16", 2.0 ** -14, 65504.0),
    ("float8_e4m3", 2.0 ** -6, 448.0),
    ("int8", float("nan"), float("nan")),   # relative; see above
)
FORMAT_NAMES = tuple(f[0] for f in FORMATS)

# Effective mantissa bits per format (implicit bit included; int8 with a
# sign bit and 7 magnitude bits).  Values more than this many powers of
# two below a tensor's max are absorbed when accumulated at the
# tensor's scale in that format.
MANTISSA_BITS = {"bfloat16": 8, "float16": 11, "float8_e4m3": 4,
                 "int8": 7}

# Exponent histogram: one bin per power of two, floor(log2|x|) clipped
# into [HIST_LO, HIST_LO + HIST_BINS).  [-40, 24) covers every value a
# healthy f32 training run produces; the under/overflow fractions pin
# the extremes exactly, the histogram is for shape (and spike drills).
HIST_LO = -40
HIST_BINS = 64

# per-entry stat block: max_abs, 4 underflow fracs, 4 overflow fracs,
# then the exponent histogram
ENTRY = 1 + 2 * len(FORMATS) + HIST_BINS

# Default per-entry sample budget for the fraction/histogram pass (the
# expensive part of collection — ~40ns/element on CPU): a deterministic
# stride sample of this many values per (component, layer).  max-abs is
# ALWAYS an exact full pass, so the hard red flags (overflow = max_abs
# past the format's max finite, and the absorption cutoff derived from
# the max exponent) never depend on the sample; only the fraction
# magnitudes carry the ~1/sqrt(n) sampling error.  This is what keeps
# the ledger's step overhead under the 5% bench sentinel.  Policy knob:
# ``TrainingNumerics(sample=0)`` forces exact full-pass fractions.
DEFAULT_SAMPLE = 1024


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class NumericsPlan:
    """Ordered layer-name inventory for one net's precision ledger:
    ``grad_names`` index the gradient and updater-moment entry blocks,
    ``act_names`` the activation block (empty when activation collection
    is off).  Built identically at trace time and harvest time, so
    entry slot k always means the same layer."""

    grad_names: Tuple[str, ...]
    act_names: Tuple[str, ...]
    policy: Any

    @property
    def collect_acts(self) -> bool:
        return bool(self.act_names)


def plan_for(net) -> Optional[NumericsPlan]:
    """The net's NumericsPlan, or None when ``conf.numerics`` is unset.
    Works for both facades (ComputationGraph detected by ``conf.nodes``)."""
    policy = getattr(net.conf, "numerics", None)
    if policy is None:
        return None
    nodes = getattr(net.conf, "nodes", None)
    if nodes is not None:  # ComputationGraph
        grad = tuple(n.name for n in nodes
                     if n.layer is not None and n.layer.has_params())
        acts = tuple(n.name for n in nodes if n.layer is not None)
    else:                  # MultiLayerNetwork
        grad = tuple(l.name for l in net.layers if l.has_params())
        acts = tuple(l.name for l in net.layers)
    if not policy.collect_activations:
        acts = ()
    return NumericsPlan(grad_names=grad, act_names=acts, policy=policy)


def wants_acts(iplan, nplan) -> bool:
    """Whether the loss function must run with ``collect_acts=True`` —
    the ONE condition all six step builders (both facades, the wrapper,
    the sync master, both ZeRO paths) share, so the aux convention
    cannot diverge between the introspection and numerics engines."""
    return ((iplan is not None and iplan.collect_acts)
            or (nplan is not None and nplan.collect_acts))


def unpack_aux(iplan, nplan, aux):
    """Normalize a loss function's aux to ``(new_net_state, new_carries,
    act_stats)`` under the combined introspection + numerics activation
    convention (supersedes ``introspection.unpack_aux`` wherever both
    engines can be live)."""
    if wants_acts(iplan, nplan):
        return aux
    new_state, carries = aux
    return new_state, carries, None


# ---------------------------------------------------------------------------
# jit-safe half
# ---------------------------------------------------------------------------

def _layout(plan: NumericsPlan) -> Dict[str, slice]:
    """Slice layout of the packed state vector: iteration, the live
    loss scale (NaN when the stability engine is off — the resilience
    telemetry joining the ledger), then one ENTRY-sized stat block per
    (component, layer): gradients, updater moments, activations."""
    L, A = len(plan.grad_names), len(plan.act_names)
    off = {"iteration": slice(0, 1), "loss_scale": slice(1, 2)}
    base = 2
    off["grad"] = slice(base, base + L * ENTRY)
    off["moment"] = slice(base + L * ENTRY, base + 2 * L * ENTRY)
    base = base + 2 * L * ENTRY
    off["act"] = slice(base, base + A * ENTRY)
    off["__size__"] = slice(0, base + A * ENTRY)
    return off


def initial_state(plan: NumericsPlan) -> Dict[str, jax.Array]:
    """Fresh device-side ledger state (``iteration`` -1 marks 'no step
    collected yet')."""
    n = _layout(plan)["__size__"].stop
    v = jnp.zeros((n,), jnp.float32).at[0].set(-1.0)
    return {"packed": v}


def ensure_state(net) -> None:
    """Make sure a numerics-enabled net carries the state subtree (nets
    initialized before the policy was set, deserialized nets)."""
    plan = plan_for(net)
    if plan is not None and STATE_KEY not in net.updater_state:
        net.updater_state[STATE_KEY] = initial_state(plan)


def split_state(upd_state):
    """(numerics subtree or None, remaining updater state) — trace-time
    split; the remainder is what ``updaters.update`` (and the
    introspection/stability splits) understand."""
    if STATE_KEY not in upd_state:
        return None, upd_state
    return (upd_state[STATE_KEY],
            {k: v for k, v in upd_state.items() if k != STATE_KEY})


def _entry_stats(tree, scale=None, sample=DEFAULT_SAMPLE) -> jax.Array:
    """One (component, layer) stat block ``[ENTRY]`` over every leaf of
    a subtree: exact max-abs (full pass), then per-format
    underflow/overflow fractions and the exponent histogram over a
    deterministic stride sample of ~``sample`` values (``sample=0`` =
    exact; see ``DEFAULT_SAMPLE``).  ``scale`` (the 1/loss_scale
    gradient unscale) multiplies values BEFORE the threshold
    comparisons — fractions do not commute with scaling, unlike the
    norms introspection collects."""
    leaves = [jnp.asarray(l).astype(jnp.float32).reshape(-1)
              for l in jax.tree_util.tree_leaves(tree)]
    leaves = [l for l in leaves if l.size]
    if not leaves:
        return jnp.zeros((ENTRY,), jnp.float32)
    if scale is not None:
        leaves = [l * scale for l in leaves]
    max_abs = jnp.zeros((), jnp.float32)
    for l in leaves:
        max_abs = jnp.maximum(max_abs, jnp.max(jnp.abs(l)))
    total = sum(l.size for l in leaves)
    if sample and total > sample:
        # one GLOBAL stride: every sampled value represents the same
        # element count, so plain sampled-count ratios are unbiased
        stride = -(-total // sample)
        stat_leaves = [l[::stride] for l in leaves]
    else:
        stat_leaves = leaves
    n = float(sum(l.size for l in stat_leaves))
    under = [jnp.zeros((), jnp.float32) for _ in FORMATS]
    over = [jnp.zeros((), jnp.float32) for _ in FORMATS]
    hist = jnp.zeros((HIST_BINS,), jnp.float32)
    # int8 per-page scale: quantizes to zero below max_abs/254
    int8_lo = max_abs / 254.0
    bins = jnp.arange(HIST_BINS)[None, :]
    for l in stat_leaves:
        a = jnp.abs(l)
        nz = a > 0
        nzf = nz.astype(jnp.float32)
        for i, (name, lo, hi) in enumerate(FORMATS):
            if name == "int8":
                under[i] = under[i] + jnp.sum(nzf * (a < int8_lo))
            else:
                under[i] = under[i] + jnp.sum(nzf * (a < lo))
                over[i] = over[i] + jnp.sum((a > hi).astype(jnp.float32))
        e = jnp.floor(jnp.log2(jnp.where(nz, a, 1.0)))
        idx = jnp.clip(e - HIST_LO, 0, HIST_BINS - 1).astype(jnp.int32)
        # one-hot compare-sum: cheaper than a scatter on small samples
        hist = hist + jnp.sum(
            ((idx[:, None] == bins) & nz[:, None]).astype(jnp.float32),
            axis=0)
    parts = [max_abs.reshape((1,)),
             jnp.stack(under) / n, jnp.stack(over) / n, hist]
    return jnp.concatenate(parts)


def _sample_of(policy) -> int:
    return int(getattr(policy, "sample", DEFAULT_SAMPLE)
               if policy is not None else DEFAULT_SAMPLE)


def _interval_of(policy) -> int:
    return int(getattr(policy, "interval", 1) or 1) if policy is not None else 1


def collect_now(plan, iteration):
    """Traced collect-this-step predicate for interval-gated collection,
    or None when the ledger collects every step (``interval <= 1``).
    The ledger is a snapshot read once per reporting window — computing
    it on every step buys nothing, so both the activation pass (inside
    the loss fn) and the gradient/moment pass (in ``attach``) branch on
    this single predicate via ``lax.cond`` and carry the stale packed
    vector through on off-steps.  Both branches compile once; zero
    recompiles."""
    if plan is None:
        return None
    interval = _interval_of(plan.policy)
    if interval <= 1:
        return None
    return (jnp.asarray(iteration, jnp.int32) % interval) == 0


def act_ranges(named_acts: Sequence[Tuple[str, jax.Array]],
               policy=None, now=None) -> Dict[str, jax.Array]:
    """Per-layer activation range stats, stacked in input order to
    ``[A, ENTRY]`` — called inside the facades' loss functions while
    the activations are still live in the graph (reduced immediately;
    the full activations are never carried out).  ``now`` (from
    ``collect_now``) skips the whole pass on off-steps; the zero block
    it returns is never read — ``attach`` carries the previous packed
    vector through on those steps."""
    sample = _sample_of(policy)

    def fresh():
        return jnp.stack(
            [_entry_stats(jax.lax.stop_gradient(a), sample=sample)
             for _, a in named_acts])

    if now is None:
        return {"num_act": fresh()}
    zeros = lambda: jnp.zeros((len(named_acts), ENTRY), jnp.float32)
    return {"num_act": jax.lax.cond(now, fresh, zeros)}


def _moments_of(upd_tree, name):
    """Every updater-moment leaf of one layer across the slot-keyed
    updater state (``{"m": {layer: ...}, "v": {layer: ...}}``); empty
    for moment-free updaters (SGD)."""
    if not isinstance(upd_tree, dict):
        return []
    return [tree[name] for tree in upd_tree.values()
            if isinstance(tree, dict) and name in tree]


def collect(plan: NumericsPlan, *, grads, upd_tree, iteration,
            act_stats=None, grad_scale=None) -> Dict[str, jax.Array]:
    """One step's refreshed ledger state.  ``grads`` are the step's raw
    gradients (loss-scaled under the stability engine — ``grad_scale``
    unscales them elementwise before the threshold stats), ``upd_tree``
    the NEW inner updater state whose moment leaves are measured, and
    ``act_stats["num_act"]`` the in-graph activation block from
    ``act_ranges``."""
    sample = _sample_of(plan.policy)
    parts = [jnp.asarray(iteration, jnp.float32).reshape((1,)),
             (jnp.asarray(1.0 / grad_scale, jnp.float32).reshape((1,))
              if grad_scale is not None
              else jnp.full((1,), jnp.nan, jnp.float32))]
    for name in plan.grad_names:
        parts.append(_entry_stats(grads.get(name, {}), scale=grad_scale,
                                  sample=sample))
    for name in plan.grad_names:
        parts.append(_entry_stats(_moments_of(upd_tree, name),
                                  sample=sample))
    if plan.act_names:
        if act_stats is None or "num_act" not in act_stats:
            raise ValueError(
                "plan collects activations but no num_act stats were "
                "passed (loss fn must run with collect_acts=True)")
        parts.append(act_stats["num_act"].reshape(-1))
    return {"packed": jnp.concatenate(parts)}


def attach(new_upd_state, plan, *, grads, iteration, act_stats=None,
           grad_scale=None, prev=None, now=None):
    """Insert the refreshed ``__numerics__`` subtree into a step's new
    updater state (no-op when the ledger is off) — the single wiring
    point the step cores share.  Moments are measured from
    ``new_upd_state`` itself (post-update, so the ledger reflects what
    the checkpoint would carry).  With ``now`` (from ``collect_now``)
    and ``prev`` (the subtree split off the incoming updater state),
    off-steps skip the whole stats pass under ``lax.cond`` and carry
    the previous packed vector through unchanged."""
    if plan is None:
        return new_upd_state

    def fresh():
        return collect(
            plan, grads=grads, upd_tree=new_upd_state,
            iteration=iteration, act_stats=act_stats,
            grad_scale=grad_scale)["packed"]

    expected = _layout(plan)["__size__"].stop
    if (now is None or prev is None
            or tuple(prev["packed"].shape) != (expected,)):
        # every-step collection, or a stale/mismatched carried state
        # (e.g. deserialized under a changed plan): recompute fresh
        new_upd_state[STATE_KEY] = {"packed": fresh()}
        return new_upd_state
    new_upd_state[STATE_KEY] = {
        "packed": jax.lax.cond(now, fresh, lambda: prev["packed"])}
    return new_upd_state


# ---------------------------------------------------------------------------
# host half: harvest, verdicts, metrics, ledger
# ---------------------------------------------------------------------------

def latest(model):
    """The most recent device-side ledger state: the masters stamp
    ``_numerics_live`` per step/window (the wrapper's stamp is the
    stacked ``[K, N]`` per-replica view); the facades' ``updater_state``
    is always current."""
    live = getattr(model, "_numerics_live", None)
    if live is not None:
        return live
    return model.updater_state.get(STATE_KEY)


def _entry_host(block: np.ndarray) -> Dict[str, Any]:
    """One host-side entry dict from an ``[ENTRY]`` (or stacked
    ``[K, ENTRY]``) stat block.  Stacked states merge conservatively:
    max-abs takes the max over replicas, fractions the finite mean,
    histograms the sum."""
    if block.ndim == 2:
        max_abs = float(np.nanmax(block[:, 0]))
        fr = np.nanmean(block[:, 1:1 + 2 * len(FORMATS)], axis=0)
        hist = np.nansum(block[:, 1 + 2 * len(FORMATS):], axis=0)
    else:
        max_abs = float(block[0])
        fr = block[1:1 + 2 * len(FORMATS)]
        hist = block[1 + 2 * len(FORMATS):]
    nf = len(FORMATS)
    return {
        "max_abs": max_abs,
        "underflow": {name: float(fr[i])
                      for i, name in enumerate(FORMAT_NAMES)},
        "overflow": {name: float(fr[nf + i])
                     for i, name in enumerate(FORMAT_NAMES)},
        "exponent_histogram": [float(c) for c in hist],
    }


def absorption_fraction(entry: Dict[str, Any], dtype: str) -> float:
    """Fraction of nonzero values more than ``MANTISSA_BITS[dtype]``
    powers of two below the entry's max exponent, read off the exponent
    histogram — values absorbed when accumulated at the tensor's scale
    in ``dtype``.  0.0 for empty/all-zero entries."""
    total = sum(entry["exponent_histogram"])
    if total <= 0 or entry["max_abs"] <= 0:
        return 0.0
    max_exp = math.floor(math.log2(entry["max_abs"]))
    cut = max_exp - MANTISSA_BITS[dtype]   # exponents < cut are absorbed
    hi_bin = min(max(cut - HIST_LO, 0), HIST_BINS)
    return float(sum(entry["exponent_histogram"][:hi_bin]) / total)


_MAX_FINITE = {name: hi for name, _lo, hi in FORMATS}


def overflow_hard(entry: Dict[str, Any], dtype: str) -> bool:
    """The EXACT overflow red flag: the entry's (full-pass) max-abs
    exceeds the format's max finite value.  Authoritative even when the
    sampled overflow fraction missed the offending elements."""
    hi = _MAX_FINITE[dtype]
    return math.isfinite(hi) and entry["max_abs"] > hi


def verdicts(entry: Dict[str, Any], policy=None) -> Dict[str, bool]:
    """Per-format safety verdict for one entry: safe iff nothing
    overflows (sampled fraction OR the exact max-abs flag), and neither
    the underflow nor the absorption fraction exceeds the policy
    threshold (default 0.5 — 'narrowing this tensor keeps at least half
    its nonzero information')."""
    thresh = getattr(policy, "absorb_threshold", 0.5) if policy else 0.5
    out = {}
    for name in FORMAT_NAMES:
        risky = (entry["overflow"][name] > 0.0
                 or overflow_hard(entry, name)
                 or entry["underflow"][name] > thresh
                 or absorption_fraction(entry, name) > thresh)
        out[name] = not risky
    return out


def risk_score(entry: Dict[str, Any], dtype: str) -> float:
    """The scalar the ``dl4j_layer_overflow_risk`` gauge publishes: the
    worst of the overflow, underflow and absorption fractions for one
    (component, layer, dtype) — 0.0 is perfectly representable, 1.0 is
    total loss.  A hard overflow (max-abs past the format's max finite)
    is 1.0 outright: the narrowed tensor would carry infs."""
    if overflow_hard(entry, dtype):
        return 1.0
    return max(entry["overflow"][dtype], entry["underflow"][dtype],
               absorption_fraction(entry, dtype))


def harvest(state, plan: NumericsPlan) -> Optional[Dict[str, Any]]:
    """Fan a device-side ledger state out into host dicts with ONE
    batched device->host transfer.  A stacked ``[K, N]`` state (the
    wrapper's per-replica view) merges per ``_entry_host``."""
    if state is None or plan is None:
        return None
    packed = np.asarray(jax.device_get(state["packed"]))
    lay = _layout(plan)
    if packed.shape[-1] != lay["__size__"].stop:
        return None   # state from a different plan shape (stale stamp)
    stacked = packed.ndim == 2
    policy = plan.policy

    def entries(key, names):
        sl = lay[key]
        blocks = packed[..., sl]
        out = {}
        for i, name in enumerate(names):
            b = blocks[..., i * ENTRY:(i + 1) * ENTRY]
            e = _entry_host(b)
            e["verdicts"] = verdicts(e, policy)
            out[name] = e
        return out

    it = packed[..., 0]
    ls = packed[..., 1]
    ls_val = float(np.nanmax(ls)) if stacked else float(ls)
    return {
        "iteration": int(it.max()) if stacked else int(it),
        "replicas": int(packed.shape[0]) if stacked else None,
        "loss_scale": ls_val if math.isfinite(ls_val) else None,
        "gradients": entries("grad", plan.grad_names),
        "moments": entries("moment", plan.grad_names),
        "activations": entries("act", plan.act_names),
    }


def harvest_model(model) -> Optional[Dict[str, Any]]:
    """``harvest(latest(model), plan_for(model))`` — the StatsListener /
    UI entry point; None when the ledger is off or nothing collected."""
    plan = plan_for(model)
    if plan is None:
        return None
    h = harvest(latest(model), plan)
    if h is not None and h["iteration"] < 0:
        return None   # state allocated but no step collected yet
    return h


_COMPONENTS = (("gradients", "grad"), ("moments", "moment"),
               ("activations", "act"))


def publish_metrics(harvested: Dict[str, Any], registry=None) -> None:
    """Mirror a harvested ledger into the gauge families.  Risk is
    published per (component, layer, dtype); max-abs per (component,
    layer) — the raw dynamic-range headline the risk derives from."""
    if registry is None:
        from deeplearning4j_tpu.observability import get_registry
        registry = get_registry()
    g_risk = registry.gauge(
        _RISK, "Per-layer fraction of values at risk (overflow, "
        "underflow-to-zero, or mantissa absorption — the worst of the "
        "three) if this component were narrowed to the labeled dtype; "
        "from the most recent precision-ledger harvest "
        "(docs/observability.md \"Numerics\")",
        labels=("component", "layer", "dtype"))
    g_max = registry.gauge(
        _MAXABS, "Per-layer max-abs value of the most recent "
        "precision-ledger harvest (dynamic-range headline the "
        "overflow-risk verdicts derive from)",
        labels=("component", "layer"))
    for comp, short in _COMPONENTS:
        for layer, e in harvested[comp].items():
            if math.isfinite(e["max_abs"]):
                g_max.set(e["max_abs"], component=short, layer=layer)
            for dtype in FORMAT_NAMES:
                r = risk_score(e, dtype)
                if math.isfinite(r):
                    g_risk.set(r, component=short, layer=layer,
                               dtype=dtype)


def format_precision_ledger(harvested: Dict[str, Any]) -> str:
    """Operator view of one harvested ledger: a fixed-width table of
    per-(component, layer) max-abs and per-format safety verdicts, the
    numerics analog of ``shardstats.format_ledger``."""
    if not harvested:
        return "precision ledger: nothing collected yet"
    lines = [f"precision ledger @ iteration {harvested['iteration']}"
             + (f" (replicas={harvested['replicas']})"
                if harvested.get("replicas") else "")
             + (f" loss_scale={harvested['loss_scale']:g}"
                if harvested.get("loss_scale") else "")]
    hdr = (f"  {'component':<10} {'layer':<28} {'max_abs':>12} "
           + " ".join(f"{n:>12}" for n in FORMAT_NAMES))
    lines.append(hdr)
    for comp, short in _COMPONENTS:
        for layer, e in harvested[comp].items():
            cells = []
            for dtype in FORMAT_NAMES:
                ok = e["verdicts"][dtype]
                cells.append(f"{'ok' if ok else 'RISK':>7} "
                             f"{risk_score(e, dtype):.2f}")
            lines.append(f"  {short:<10} {layer:<28} {e['max_abs']:>12.4g} "
                         + " ".join(f"{c:>12}" for c in cells))
    return "\n".join(lines)


class NumericsMonitor:
    """Per-report anomaly rules over harvested ledger stats: a layer
    whose bf16 safety verdict goes risky (on any component) emits ONE
    rate-limited warning + a ``numerics_anomaly`` flight event naming
    the layer, component and offending format — the alarm the
    ``poison_gradients(mode="spike")`` drill asserts fires."""

    def __init__(self, component: str = "training",
                 watch_formats: Sequence[str] = ("bfloat16",),
                 min_iteration: int = 1, warn_interval_s: float = 30.0,
                 warn=None):
        self.component = component
        self.watch_formats = tuple(watch_formats)
        self.min_iteration = int(min_iteration)
        self.warn_interval_s = float(warn_interval_s)
        self.warn = warn or logger.warning
        self._lock = threading.Lock()
        self._last_warn: Dict[Tuple[str, str, str], float] = {}

    def check(self, harvested: Optional[Dict[str, Any]],
              iteration: Optional[int] = None) -> List[Dict[str, Any]]:
        if harvested is None:
            return []
        it = harvested.get("iteration", iteration) or 0
        if it < self.min_iteration:
            return []
        violations: List[Dict[str, Any]] = []
        for comp, short in _COMPONENTS:
            for layer, e in harvested[comp].items():
                for dtype in self.watch_formats:
                    if not e["verdicts"].get(dtype, True):
                        violations.append({
                            "rule": "format_safety", "layer": layer,
                            "component": short, "dtype": dtype,
                            "value": risk_score(e, dtype),
                            "max_abs": e["max_abs"]})
        for v in violations:
            self._emit(v, it)
        return violations

    def _emit(self, v: Dict[str, Any], iteration: int) -> None:
        key = (v["layer"], v["component"], v["dtype"])
        now = time.monotonic()
        with self._lock:
            if now - self._last_warn.get(key, -math.inf) \
                    < self.warn_interval_s:
                return
            self._last_warn[key] = now
        from deeplearning4j_tpu.observability import get_flight_recorder
        get_flight_recorder().record(
            "numerics_anomaly", component=self.component,
            rule=v["rule"], layer=v["layer"],
            tensor_component=v["component"], dtype=v["dtype"],
            value=float(v["value"]), iteration=int(iteration))
        self.warn(
            f"numerics anomaly in {self.component}: {v['component']} of "
            f"layer '{v['layer']}' is not {v['dtype']}-safe "
            f"(risk {v['value']:.3f}, max_abs {v['max_abs']:.4g}) "
            f"at iteration {iteration}")


# ---------------------------------------------------------------------------
# paged-KV-cache page ledger (generation engine)
# ---------------------------------------------------------------------------

def kv_page_ledger(pools: Dict[str, Any], page_size: int,
                   allocated: Optional[Sequence[int]] = None
                   ) -> Dict[str, Any]:
    """Per-page dynamic-range stats over the generation engine's paged
    KV pools — the int8-quantization-readiness evidence for ROADMAP
    item 3 (per-page scale = page max_abs / 127; a page is 'int8-ready'
    when at most half its nonzero values would quantize to zero).

    ``pools``: ``{layer: {"pk": [P, page_size, Hkv, D], "pv": ...}}``
    (the engine's live pools; nested sub-layer dicts are walked and
    joined with ``/``, and a flat ``[P*page_size, ...]`` leading axis
    also works).  ``allocated``: page ids to report (defaults to every
    non-trash page).  ONE device_get per pool leaf; host-side numpy
    reductions after that — this is an operator/report surface, never
    called inside the decode loop."""
    def _leaf_pools(tree, prefix=""):
        # {"layer_1": {"sub1": {"pk": arr, "pv": arr}}} ->
        #   ("layer_1/sub1", {"pk": arr, "pv": arr})
        if all(not isinstance(v, dict) for v in tree.values()):
            yield prefix, tree
            return
        for key, sub in tree.items():
            name = f"{prefix}/{key}" if prefix else str(key)
            yield from _leaf_pools(sub, name)

    out: Dict[str, Any] = {}
    for layer, pool in _leaf_pools(pools):
        layer_entry: Dict[str, Any] = {}
        for leaf_name, arr in pool.items():
            a = np.abs(np.asarray(jax.device_get(arr), np.float32))
            if a.ndim >= 2 and a.shape[1] == page_size:
                total = a.shape[0]            # [P, page_size, ...]
            else:                             # flat [P*page_size, ...]
                total = a.shape[0] // page_size
                a = a[:total * page_size].reshape(
                    (total, page_size) + a.shape[1:])
            pages = (list(allocated) if allocated is not None
                     else list(range(1, total)))   # page 0 = TRASH
            per = a.reshape(total, page_size, -1)
            max_abs, under, nonzero = [], [], []
            for p in pages:
                page = per[p]
                m = float(page.max()) if page.size else 0.0
                nz = page > 0
                n_nz = int(nz.sum())
                u = (float(((page < m / 254.0) & nz).sum()) / n_nz
                     if n_nz else 0.0)
                max_abs.append(m)
                under.append(u)
                nonzero.append(n_nz)
            ready = [u <= 0.5 for u in under]
            layer_entry[leaf_name] = {
                "pages": pages,
                "page_max_abs": max_abs,
                "int8_underflow": under,
                "nonzero_counts": nonzero,
                "int8_ready_fraction": (
                    sum(ready) / len(ready) if ready else 1.0),
            }
        out[layer] = layer_entry
    return out
