"""Lightweight span tracer: context-manager API, monotonic clocks,
parent/child nesting, JSON-lines export.

Spans are host-side wall-time markers around *dispatch* (on TPU the device
work is async — a span brackets what the host did, which is exactly the
phase-attribution SparkNet/DeepSpark-style throughput tuning needs).  For
*device* time, enable the optional jax-profiler passthrough: with
``use_jax_profiler=True`` every span also enters a
``jax.profiler.TraceAnnotation`` so spans line up with XLA ops in the
TensorBoard profile, and ``SpanTracer.profile(log_dir)`` brackets a whole
region with ``jax.profiler.start_trace``/``stop_trace``.

Request tracing: serving mints (or accepts via ``X-Request-Id``) a
``trace_id`` per request and stamps it on the per-stage spans
(``serving_request`` / ``serving_queue_wait`` / ``serving_execute``), so
``spans_for_trace(trace_id)`` answers "where did THIS request's time go".
``export_chrome_trace`` renders any span set as Chrome-trace JSON
(loadable in ``chrome://tracing`` / Perfetto).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple


def new_trace_id() -> str:
    """A 16-hex-char request trace id (random; no global coordination)."""
    return os.urandom(8).hex()


class Span:
    """One finished (or in-flight) span."""

    __slots__ = ("name", "span_id", "parent_id", "start_ns", "end_ns",
                 "attrs", "thread")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 start_ns: int, attrs: Dict[str, Any],
                 thread: Optional[str] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns: Optional[int] = None
        self.attrs = attrs
        self.thread = (thread if thread is not None
                       else threading.current_thread().name)

    @property
    def duration_ns(self) -> Optional[int]:
        if self.end_ns is None:
            return None
        return self.end_ns - self.start_ns

    @property
    def duration_ms(self) -> Optional[float]:
        d = self.duration_ns
        return None if d is None else d / 1e6

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "duration_ns": self.duration_ns,
            "attrs": self.attrs,
            "thread": self.thread,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Span":
        s = Span(d["name"], d["span_id"], d.get("parent_id"),
                 d["start_ns"], d.get("attrs") or {},
                 thread=d.get("thread") or "unknown")
        s.end_ns = d.get("end_ns")
        return s


class SpanTracer:
    """Nesting tracer with a bounded in-memory buffer of finished spans.

    Per-thread parent tracking (a serving handler thread and the training
    loop can both trace without cross-linking), monotonic
    ``perf_counter_ns`` clocks, O(1) memory via a ``deque(maxlen=...)``.
    """

    def __init__(self, max_spans: int = 4096,
                 use_jax_profiler: bool = False):
        self.max_spans = max_spans
        self.use_jax_profiler = use_jax_profiler
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._finished: deque = deque(maxlen=max_spans)
        # registration id -> (thread object, live stack list).  The stack
        # is the SAME list the owning thread mutates; registering it here
        # lets the watchdog read every thread's in-flight spans at dump
        # time.  Keyed by a monotonic id, NOT thread ident: CPython
        # recycles idents immediately, so a new thread would overwrite a
        # dead thread's retained open-span entry — exactly the crash
        # evidence live_spans() promises to keep.
        self._live: Dict[int, Tuple[threading.Thread, List[Span]]] = {}
        self._live_ids = itertools.count(1)
        self.dropped = 0  # finished spans evicted by the bound

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
            t = threading.current_thread()
            with self._lock:
                self._live[next(self._live_ids)] = (t, st)
        return st

    def live_spans(self) -> List[Dict[str, Any]]:
        """In-flight (unfinished) spans across ALL threads, outermost
        first per thread, each dict annotated with ``thread`` and
        ``depth``.  Reading copies each stack once; the owning thread may
        race an append/pop, which at worst makes the copy one span stale
        — acceptable for a diagnosis dump, and safe under CPython.

        Entries for threads that have exited with an EMPTY stack are
        pruned here (thread churn — per-fit prefetch workers, handler
        threads — must not grow ``_live`` for the process lifetime); a
        dead thread that still holds open spans is kept, since "this
        thread died inside span X" is exactly what a crash dump needs."""
        with self._lock:
            for rid in [rid for rid, (t, st) in self._live.items()
                        if not t.is_alive() and not st]:
                del self._live[rid]
            stacks = list(self._live.values())
        out: List[Dict[str, Any]] = []
        for t, stack in stacks:
            for depth, s in enumerate(list(stack)):
                d = s.to_dict()
                d["thread"] = t.name
                d["depth"] = depth
                out.append(d)
        return out

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        s = Span(name, next(self._ids), parent, time.perf_counter_ns(), attrs)
        stack.append(s)
        annot = None
        if self.use_jax_profiler:
            try:
                import jax

                annot = jax.profiler.TraceAnnotation(name)
                annot.__enter__()
            except Exception:
                annot = None
        try:
            yield s
        finally:
            if annot is not None:
                annot.__exit__(None, None, None)
            s.end_ns = time.perf_counter_ns()
            stack.pop()
            with self._lock:
                if len(self._finished) == self._finished.maxlen:
                    self.dropped += 1
                self._finished.append(s)

    @contextmanager
    def profile(self, log_dir: str) -> Iterator[None]:
        """Bracket a region with a jax profiler trace (XPlane/TensorBoard);
        no-ops if the profiler is unavailable."""
        started = False
        try:
            import jax

            jax.profiler.start_trace(str(log_dir))
            started = True
        except Exception:
            pass
        try:
            yield
        finally:
            if started:
                import jax

                jax.profiler.stop_trace()

    def record_span(self, name: str, start_ns: int, end_ns: int,
                    **attrs) -> Span:
        """Record an already-timed span directly (no stack involvement):
        the batcher uses this for queue-wait and execute stages whose
        start happened on a different thread than their end.  Clocks are
        ``perf_counter_ns`` like everything else here."""
        s = Span(name, next(self._ids), None, int(start_ns), attrs)
        s.end_ns = int(end_ns)
        with self._lock:
            if len(self._finished) == self._finished.maxlen:
                self.dropped += 1
            self._finished.append(s)
        return s

    # -------------------------------------------------------------- queries
    def spans_for_trace(self, trace_id: str) -> List[Span]:
        """Finished spans stamped with ``trace_id=`` (request tracing):
        the per-stage breakdown of one serving request."""
        return [s for s in self.spans()
                if s.attrs.get("trace_id") == trace_id]

    def spans_between(self, start_ns: int, end_ns: int) -> List[Span]:
        """Finished spans overlapping the [start_ns, end_ns) window (the
        profiler's capture export)."""
        out = []
        for s in self.spans():
            if s.start_ns < end_ns and (s.end_ns or end_ns) > start_ns:
                out.append(s)
        return out

    # ------------------------------------------------------------- export
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(s.to_dict()) for s in self.spans())

    def export_jsonl(self, path: str) -> int:
        """Write finished spans as JSON lines; returns the span count."""
        spans = self.spans()
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(s.to_dict()) + "\n")
        return len(spans)

    def to_chrome_trace(self, spans: Optional[List[Span]] = None) -> Dict:
        """Render spans as the Chrome trace event format (``ph: "X"``
        complete events, microsecond clocks) — loadable in
        ``chrome://tracing`` and Perfetto with no TensorBoard plugin.
        Threads become trace ``tid``s with ``thread_name`` metadata."""
        spans = self.spans() if spans is None else spans
        pid = os.getpid()
        tids: Dict[str, int] = {}
        events: List[Dict[str, Any]] = []
        for s in spans:
            if s.end_ns is None:
                continue
            tid = tids.setdefault(s.thread, len(tids) + 1)
            events.append({
                "name": s.name, "cat": "span", "ph": "X",
                "ts": s.start_ns / 1e3, "dur": (s.end_ns - s.start_ns) / 1e3,
                "pid": pid, "tid": tid,
                "args": {**s.attrs, "span_id": s.span_id,
                         "parent_id": s.parent_id},
            })
        meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                 "args": {"name": thread}} for thread, tid in tids.items()]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str,
                            spans: Optional[List[Span]] = None) -> int:
        """Write a Chrome-trace JSON file; returns the span event count."""
        doc = self.to_chrome_trace(spans)
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
        return sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")

    @staticmethod
    def read_jsonl(path: str) -> List[Span]:
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(Span.from_dict(json.loads(line)))
        return out

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            self.dropped = 0


_global_lock = threading.Lock()
_global_tracer: Optional[SpanTracer] = None


def get_tracer() -> SpanTracer:
    """The process-wide default tracer (created on first use)."""
    global _global_tracer
    with _global_lock:
        if _global_tracer is None:
            _global_tracer = SpanTracer()
        return _global_tracer


def set_tracer(tracer: Optional[SpanTracer]) -> SpanTracer:
    """Swap the process-wide tracer (tests / profiling runs)."""
    global _global_tracer
    with _global_lock:
        _global_tracer = tracer or SpanTracer()
        return _global_tracer
