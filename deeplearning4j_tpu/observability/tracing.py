"""Lightweight span tracer: context-manager API, monotonic clocks,
parent/child nesting, JSON-lines export.

Spans are host-side wall-time markers around *dispatch* (on TPU the device
work is async — a span brackets what the host did, which is exactly the
phase-attribution SparkNet/DeepSpark-style throughput tuning needs).  For
*device* time, enable the optional jax-profiler passthrough: with
``use_jax_profiler=True`` every span also enters a
``jax.profiler.TraceAnnotation`` so spans line up with XLA ops in the
TensorBoard profile, and ``SpanTracer.profile(log_dir)`` brackets a whole
region with ``jax.profiler.start_trace``/``stop_trace``.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple


class Span:
    """One finished (or in-flight) span."""

    __slots__ = ("name", "span_id", "parent_id", "start_ns", "end_ns",
                 "attrs")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 start_ns: int, attrs: Dict[str, Any]):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns: Optional[int] = None
        self.attrs = attrs

    @property
    def duration_ns(self) -> Optional[int]:
        if self.end_ns is None:
            return None
        return self.end_ns - self.start_ns

    @property
    def duration_ms(self) -> Optional[float]:
        d = self.duration_ns
        return None if d is None else d / 1e6

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "duration_ns": self.duration_ns,
            "attrs": self.attrs,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Span":
        s = Span(d["name"], d["span_id"], d.get("parent_id"),
                 d["start_ns"], d.get("attrs") or {})
        s.end_ns = d.get("end_ns")
        return s


class SpanTracer:
    """Nesting tracer with a bounded in-memory buffer of finished spans.

    Per-thread parent tracking (a serving handler thread and the training
    loop can both trace without cross-linking), monotonic
    ``perf_counter_ns`` clocks, O(1) memory via a ``deque(maxlen=...)``.
    """

    def __init__(self, max_spans: int = 4096,
                 use_jax_profiler: bool = False):
        self.max_spans = max_spans
        self.use_jax_profiler = use_jax_profiler
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._finished: deque = deque(maxlen=max_spans)
        # registration id -> (thread object, live stack list).  The stack
        # is the SAME list the owning thread mutates; registering it here
        # lets the watchdog read every thread's in-flight spans at dump
        # time.  Keyed by a monotonic id, NOT thread ident: CPython
        # recycles idents immediately, so a new thread would overwrite a
        # dead thread's retained open-span entry — exactly the crash
        # evidence live_spans() promises to keep.
        self._live: Dict[int, Tuple[threading.Thread, List[Span]]] = {}
        self._live_ids = itertools.count(1)
        self.dropped = 0  # finished spans evicted by the bound

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
            t = threading.current_thread()
            with self._lock:
                self._live[next(self._live_ids)] = (t, st)
        return st

    def live_spans(self) -> List[Dict[str, Any]]:
        """In-flight (unfinished) spans across ALL threads, outermost
        first per thread, each dict annotated with ``thread`` and
        ``depth``.  Reading copies each stack once; the owning thread may
        race an append/pop, which at worst makes the copy one span stale
        — acceptable for a diagnosis dump, and safe under CPython.

        Entries for threads that have exited with an EMPTY stack are
        pruned here (thread churn — per-fit prefetch workers, handler
        threads — must not grow ``_live`` for the process lifetime); a
        dead thread that still holds open spans is kept, since "this
        thread died inside span X" is exactly what a crash dump needs."""
        with self._lock:
            for rid in [rid for rid, (t, st) in self._live.items()
                        if not t.is_alive() and not st]:
                del self._live[rid]
            stacks = list(self._live.values())
        out: List[Dict[str, Any]] = []
        for t, stack in stacks:
            for depth, s in enumerate(list(stack)):
                d = s.to_dict()
                d["thread"] = t.name
                d["depth"] = depth
                out.append(d)
        return out

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        s = Span(name, next(self._ids), parent, time.perf_counter_ns(), attrs)
        stack.append(s)
        annot = None
        if self.use_jax_profiler:
            try:
                import jax

                annot = jax.profiler.TraceAnnotation(name)
                annot.__enter__()
            except Exception:
                annot = None
        try:
            yield s
        finally:
            if annot is not None:
                annot.__exit__(None, None, None)
            s.end_ns = time.perf_counter_ns()
            stack.pop()
            with self._lock:
                if len(self._finished) == self._finished.maxlen:
                    self.dropped += 1
                self._finished.append(s)

    @contextmanager
    def profile(self, log_dir: str) -> Iterator[None]:
        """Bracket a region with a jax profiler trace (XPlane/TensorBoard);
        no-ops if the profiler is unavailable."""
        started = False
        try:
            import jax

            jax.profiler.start_trace(str(log_dir))
            started = True
        except Exception:
            pass
        try:
            yield
        finally:
            if started:
                import jax

                jax.profiler.stop_trace()

    # ------------------------------------------------------------- export
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(s.to_dict()) for s in self.spans())

    def export_jsonl(self, path: str) -> int:
        """Write finished spans as JSON lines; returns the span count."""
        spans = self.spans()
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(s.to_dict()) + "\n")
        return len(spans)

    @staticmethod
    def read_jsonl(path: str) -> List[Span]:
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(Span.from_dict(json.loads(line)))
        return out

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            self.dropped = 0


_global_lock = threading.Lock()
_global_tracer: Optional[SpanTracer] = None


def get_tracer() -> SpanTracer:
    """The process-wide default tracer (created on first use)."""
    global _global_tracer
    with _global_lock:
        if _global_tracer is None:
            _global_tracer = SpanTracer()
        return _global_tracer


def set_tracer(tracer: Optional[SpanTracer]) -> SpanTracer:
    """Swap the process-wide tracer (tests / profiling runs)."""
    global _global_tracer
    with _global_lock:
        _global_tracer = tracer or SpanTracer()
        return _global_tracer
