"""Process-wide metrics registry: counters, gauges, histograms with labels.

The data model every layer shares (SURVEY.md: the reference scatters timing
over ``IterationListener`` / ``PerformanceListener`` / the SBE-encoded
``StatsListener`` pipeline with no common store; SparkNet/DeepSpark show
that distributed-throughput tuning needs one).  Naming follows Prometheus
conventions — ``dl4j_`` prefix, base units (seconds, bytes), ``_total``
suffix on counters — and the registry renders both JSON (``to_json``) and
Prometheus text exposition format (``to_prometheus``).

TPU-specific design point: gauges accept LAZY values — an on-device scalar
(or a zero-arg callable) is stored as-is and only converted with
``float()`` at scrape/render time, so the training hot loop never pays a
device->host sync to record its score (the same contract as
``LazyScoreMixin``).
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# Latency buckets in SECONDS (Prometheus base unit), spanning the sub-ms
# dispatch floor of LeNet-class steps up to multi-second ResNet/compile
# events.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _as_float(v: Any) -> float:
    """Resolve a lazily-stored gauge value (callable or device scalar).
    A raising callback degrades to NaN — a broken live gauge must never
    take down a /metrics scrape or a flight-recorder dump."""
    if callable(v):
        try:
            v = v()
        except Exception:
            return float("nan")
    try:
        return float(v)
    except (TypeError, ValueError):
        return float("nan")


def _escape_label(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(pairs: Sequence[Tuple[str, Any]]) -> str:
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class Counter:
    """Monotonically increasing value (one labeled child of a family)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        # dl4jlint: disable-next-line=lock-discipline -- monitoring read of a GIL-atomic float; scrapes tolerate one stale increment
        return self._value


class Gauge:
    """Point-in-time value; accepts lazy values (device scalar / callable)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value: Any = 0.0

    def set(self, value: Any) -> None:
        """Store without conversion: an on-device scalar stays on device
        until scrape time (no sync in the hot loop)."""
        # dl4jlint: disable-next-line=lock-discipline -- blind GIL-atomic reference publish from the single hot-loop writer; inc() locks because it read-modify-writes
        self._value = value

    def set_function(self, fn) -> None:
        """Gauge computed at scrape time (e.g. a queue depth)."""
        # dl4jlint: disable-next-line=lock-discipline -- blind GIL-atomic reference publish (see set)
        self._value = fn

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value = _as_float(self._value) + amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        # dl4jlint: disable-next-line=lock-discipline -- monitoring read of a GIL-atomic reference; scrapes tolerate one stale set
        return _as_float(self._value)


class Histogram:
    """Cumulative-bucket histogram + running sum/count/min/max.

    min/max are beyond the Prometheus exposition model but kept so
    registry-backed phase timers can reproduce the ``PhaseStats.as_dict``
    schema exactly (count/total/mean/min/max per phase).
    """

    __slots__ = ("_lock", "buckets", "_bucket_counts", "_sum", "_count",
                 "_min", "_max", "_exemplars")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._bucket_counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        # bucket index -> last exemplar that landed there (OpenMetrics
        # style: a trace id sampled onto the latency distribution, so a
        # p99 spike comes with a concrete request to go look at).  Index
        # len(buckets) is the +Inf overflow bucket.
        self._exemplars: Dict[int, Dict[str, Any]] = {}

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value
            idx = len(self.buckets)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._bucket_counts[i] += 1
                    idx = i
                    break
            if exemplar is not None:
                self._exemplars[idx] = {"trace_id": str(exemplar),
                                        "value": value, "ts": time.time()}

    def time(self):
        """Context manager observing the elapsed seconds of the block."""
        return _HistogramTimer(self)

    def restore(self, bucket_counts: Sequence[int], sum: float, count: int,
                min: Optional[float] = None,
                max: Optional[float] = None) -> None:
        """Overwrite this histogram's state from an exported snapshot —
        the import half of the fleet-federation wire format
        (observability.fleet): per-bucket counts, running sum/count, and
        optional min/max (NaN when the exporter didn't carry them, so a
        merged histogram never fabricates extremes)."""
        bucket_counts = [int(c) for c in bucket_counts]
        if len(bucket_counts) != len(self.buckets):
            raise ValueError(
                f"restore() got {len(bucket_counts)} bucket counts for "
                f"{len(self.buckets)} buckets")
        with self._lock:
            self._bucket_counts = bucket_counts
            self._sum = float(sum)
            self._count = int(count)
            if self._count:
                self._min = float("nan") if min is None else float(min)
                self._max = float("nan") if max is None else float(max)
            else:
                self._min = math.inf
                self._max = -math.inf

    @property
    def count(self) -> int:
        # dl4jlint: disable-next-line=lock-discipline -- monitoring read of one GIL-atomic int; snapshot() is the consistent view
        return self._count

    @property
    def sum(self) -> float:
        # dl4jlint: disable-next-line=lock-discipline -- monitoring read of one GIL-atomic float; snapshot() is the consistent view
        return self._sum

    @property
    def min(self) -> float:
        # dl4jlint: disable-next-line=lock-discipline -- monitoring read; count/min may straddle an observe, snapshot() is the consistent view
        return self._min if self._count else float("nan")

    @property
    def max(self) -> float:
        # dl4jlint: disable-next-line=lock-discipline -- monitoring read; count/max may straddle an observe, snapshot() is the consistent view
        return self._max if self._count else float("nan")

    def snapshot(self) -> Dict[str, Any]:
        """One CONSISTENT view of the histogram taken under a single lock
        acquisition: count/sum/min/max and the cumulative buckets all
        describe the same instant, even while other threads keep
        observing (the watchdog, the dispatcher, and the fit loop now
        read histograms concurrently with writers)."""
        with self._lock:
            count = self._count
            bucket_counts = list(self._bucket_counts)
            exemplars = {i: dict(e) for i, e in self._exemplars.items()}
            out = {
                "count": count,
                "sum": self._sum,
                "min": self._min if count else None,
                "max": self._max if count else None,
            }
        out["exemplars"] = {
            ("+Inf" if i == len(self.buckets)
             else _fmt_value(self.buckets[i])): e
            for i, e in exemplars.items()}
        cum, running = [], 0
        for b, c in zip(self.buckets, bucket_counts):
            running += c
            cum.append((b, running))
        cum.append((math.inf, count))
        out["cumulative_buckets"] = cum
        out["bucket_counts"] = bucket_counts
        return out

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs, +Inf last."""
        return self.snapshot()["cumulative_buckets"]

    def exemplars(self) -> Dict[str, Dict[str, Any]]:
        """Bucket upper-bound -> last exemplar sampled into that bucket."""
        return self.snapshot()["exemplars"]

    def to_dict(self) -> Dict[str, Any]:
        snap = self.snapshot()
        out = {
            "count": snap["count"],
            "sum": snap["sum"],
            "min": snap["min"],
            "max": snap["max"],
            "buckets": {
                _fmt_value(b): c
                for b, c in zip(self.buckets, snap["bucket_counts"])
            },
        }
        if snap["exemplars"]:
            out["exemplars"] = snap["exemplars"]
        return out


class _HistogramTimer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        self._hist.observe(time.perf_counter() - self._t0)
        return False


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """A named metric plus its labeled children.  With no declared labels
    the family proxies its single unlabeled child, so
    ``registry.counter("x").inc()`` works without a ``labels()`` hop."""

    def __init__(self, name: str, kind: str, help: str,
                 label_names: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        self._buckets = tuple(buckets)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[Any, ...], Any] = {}

    def labels(self, **labels) -> Any:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name!r} declares labels {self.label_names}, "
                f"got {tuple(labels)}")
        key = tuple(labels[k] for k in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                cls = _KINDS[self.kind]
                child = (cls(self._buckets) if self.kind == "histogram"
                         else cls())
                self._children[key] = child
            return child

    # unlabeled convenience: family proxies its single child
    def _default(self):
        return self.labels()

    def inc(self, amount: float = 1.0, **labels):
        (self.labels(**labels) if labels else self._default()).inc(amount)

    def set(self, value, **labels):
        (self.labels(**labels) if labels else self._default()).set(value)

    def set_function(self, fn, **labels):
        (self.labels(**labels) if labels else self._default()).set_function(fn)

    def observe(self, value, exemplar=None, **labels):
        (self.labels(**labels) if labels
         else self._default()).observe(value, exemplar=exemplar)

    def time(self, **labels):
        return (self.labels(**labels) if labels else self._default()).time()

    def samples(self) -> List[Tuple[Tuple[Tuple[str, Any], ...], Any]]:
        with self._lock:
            items = list(self._children.items())
        return [(tuple(zip(self.label_names, key)), child)
                for key, child in items]

    def get(self, **labels):
        """Existing child or None (no implicit creation)."""
        key = tuple(labels.get(k) for k in self.label_names)
        with self._lock:
            return self._children.get(key)


class MetricsRegistry:
    """Process-wide metric store; export as JSON or Prometheus text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}

    # ------------------------------------------------------------ creation
    def _family(self, name: str, kind: str, help: str,
                labels: Sequence[str], buckets=None) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}, "
                        f"requested {kind}")
                if tuple(labels) != fam.label_names:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.label_names}, requested {tuple(labels)}")
                return fam
            fam = MetricFamily(name, kind, help, labels,
                               buckets or DEFAULT_BUCKETS)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> MetricFamily:
        return self._family(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> MetricFamily:
        return self._family(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> MetricFamily:
        return self._family(name, "histogram", help, labels, buckets)

    # ------------------------------------------------------------- reading
    def families(self) -> List[MetricFamily]:
        with self._lock:
            return list(self._families.values())

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def get_value(self, name: str, **labels) -> Optional[float]:
        """Scalar value of a counter/gauge child, or None if absent."""
        fam = self.get(name)
        if fam is None:
            return None
        child = fam.get(**labels) if labels else fam.get()
        if child is None:
            return None
        return child.value if not isinstance(child, Histogram) else None

    def family_total(self, name: str, **labels) -> float:
        """Sum of a counter/gauge family's children matching ``labels``
        (0.0 when the family is absent) — the read used by summed-counter
        consumers (early-stopping's non-finite guard, bench snapshots)."""
        fam = self.get(name)
        if fam is None:
            return 0.0
        total = 0.0
        for label_pairs, child in fam.samples():
            if isinstance(child, Histogram):
                continue
            d = dict(label_pairs)
            if all(d.get(k) == v for k, v in labels.items()):
                total += child.value
        return total

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for fam in self.families():
            vals = []
            for label_pairs, child in fam.samples():
                entry: Dict[str, Any] = {"labels": dict(label_pairs)}
                if isinstance(child, Histogram):
                    entry.update(child.to_dict())
                else:
                    entry["value"] = child.value
                vals.append(entry)
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": vals}
        return out

    def to_json_str(self, **kw) -> str:
        return json.dumps(self.to_json(), **kw)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for label_pairs, child in fam.samples():
                base = list(label_pairs)
                if isinstance(child, Histogram):
                    # one consistent snapshot per child: sum/count/buckets
                    # must describe the same instant under concurrent
                    # observe() calls
                    snap = child.snapshot()
                    for bound, cum in snap["cumulative_buckets"]:
                        le = "+Inf" if math.isinf(bound) else _fmt_value(bound)
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_fmt_labels(base + [('le', le)])} {cum}")
                    lines.append(
                        f"{fam.name}_sum{_fmt_labels(base)} "
                        f"{_fmt_value(snap['sum'])}")
                    lines.append(
                        f"{fam.name}_count{_fmt_labels(base)} "
                        f"{snap['count']}")
                else:
                    lines.append(
                        f"{fam.name}{_fmt_labels(base)} "
                        f"{_fmt_value(child.value)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._families.clear()


_global_lock = threading.Lock()
_global_registry: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created on first use)."""
    global _global_registry
    with _global_lock:
        if _global_registry is None:
            _global_registry = MetricsRegistry()
        return _global_registry


def set_registry(registry: Optional[MetricsRegistry]) -> MetricsRegistry:
    """Swap the process-wide registry (tests); returns the new one."""
    global _global_registry
    with _global_lock:
        _global_registry = registry or MetricsRegistry()
        return _global_registry
