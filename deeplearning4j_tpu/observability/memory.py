"""Device-memory gauges: PJRT ``memory_stats()`` sampled into the registry.

≙ the reference's JVM/GC memory MX-bean sampling in ``StatsListener.java``
— here the scarce resource is HBM, and PJRT exposes it per device.  CPU
backends typically return no stats; everything degrades to a graceful
no-op there (the gauges simply never appear).

``DeviceMemoryMonitor`` samples on a configurable interval from a daemon
thread; ``sample_once()`` is the synchronous one-shot both the monitor and
``ui.stats.StatsListener`` reports share.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_GAUGE = "dl4j_device_memory_bytes"
_STATS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats() -> Dict[str, Any]:
    """Per-device PJRT memory stats; empty dict when unavailable (CPU)."""
    import jax

    out: Dict[str, Any] = {}
    for i, d in enumerate(jax.local_devices()):
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if ms:
            out[f"device_{i}"] = {k: ms.get(k) for k in _STATS}
    return out


def sample_once(registry=None) -> Dict[str, Any]:
    """One sample: fetch PJRT stats and mirror them into registry gauges
    ``dl4j_device_memory_bytes{device=..., stat=...}``.  Returns the raw
    per-device dict (the shape ``ui.stats`` reports embed)."""
    from deeplearning4j_tpu.observability.metrics import get_registry

    stats = device_memory_stats()
    if stats:
        fam = (registry if registry is not None else get_registry()).gauge(
            _GAUGE, "PJRT per-device memory stats (absent on backends "
            "without memory_stats, e.g. CPU)", labels=("device", "stat"))
        for dev, per in stats.items():
            for stat, v in per.items():
                if v is not None:
                    fam.set(v, device=dev, stat=stat)
    return stats


class DeviceMemoryMonitor:
    """Background sampler: calls ``sample_once`` every ``interval_s``
    seconds from a daemon thread until ``stop()``.

    Usage::

        mon = DeviceMemoryMonitor(interval_s=10.0).start()
        ...
        mon.stop()
    """

    def __init__(self, interval_s: float = 10.0, registry=None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = float(interval_s)
        self._registry = registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    def _run(self):
        while not self._stop.is_set():
            try:
                sample_once(self._registry)
                self.samples += 1
            except Exception:
                pass  # a flaky backend must not kill the sampler thread
            self._stop.wait(self.interval_s)

    def start(self) -> "DeviceMemoryMonitor":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="dl4j-memory-monitor", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
