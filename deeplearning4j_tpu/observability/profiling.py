"""Performance attribution: step profiling, XLA cost analysis, MFU gauges,
and memory attribution.

PR-1 told us *that* a step happened (``dl4j_fit_step_seconds``); PR-3 told
us *that* a worker was slow (straggler verdicts).  Neither says where the
time and bytes went.  This module closes that gap with the modern
equivalent of the reference's per-op ``StatsListener`` timing — measured at
the compiler seam instead of per layer (the cuDNN helper-seam argument:
measure the primitive, not just the loop):

- **XLA cost analysis** (``Compiled.cost_analysis()``): flops and bytes
  accessed per compiled signature, harvested once per compile through the
  PR-1 ``RecompileDetector`` seam (``recompile._InstrumentedJit``) so every
  fit loop, every parallel master, and the pipeline master report FLOPs
  without touching their hot loops.
- **MFU / roofline gauges**: ``dl4j_step_flops_total{fn=}``,
  ``dl4j_model_flops_utilization{component=}`` (step FLOP/s over the
  backend's peak — the per-backend table below; the CPU peak is a
  documented order-of-magnitude ESTIMATE, and MFU is clamped to 1.0 so an
  underestimated peak can never report an impossible > 1 utilization),
  and ``dl4j_step_bytes_per_flop{component=}`` (XLA bytes-accessed /
  flops: a roofline position — high means memory-bound).
- **On-demand / trigger-driven trace capture** (``StepProfiler``): capture
  step N, capture the next step after a straggler verdict (PR-3 detector)
  or a watchdog hang dump, or ``request_capture()`` manually.  Each
  capture wraps the step in ``jax.profiler`` (TensorBoard XPlane + the
  gzipped Chrome trace the plugin writes) AND exports the host-side span
  window as a plain Chrome-trace JSON (``host_spans.trace.json`` —
  loadable in ``chrome://tracing`` / Perfetto with no TensorBoard), under
  a bounded on-disk budget (oldest capture directories deleted first).
- **Memory attribution**: per-leaf param/updater/net-state byte breakdown
  of tracked models, live-buffer snapshots grouped by shape/dtype, and a
  per-step peak-allocation gauge — all surfaced in flight-recorder dumps
  so a watchdog/crash report shows *what held memory*.

Cost note: cost analysis lowers+compiles the step once more per NEW
signature (``jit.lower().compile()`` does not share the dispatch cache).
Steady-state training has a closed signature set, so this is a one-off
per-shape cost paid only while a profiler is installed.

Hot-loop cost while installed: one dict write per dispatch
(``note_dispatch``) and a few gauge stores per step; nothing here ever
forces a device->host sync.
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

_FLOPS = "dl4j_step_flops_total"
_MFU = "dl4j_model_flops_utilization"
_BPF = "dl4j_step_bytes_per_flop"
_PEAK = "dl4j_backend_peak_flops"
_CAPTURES = "dl4j_profile_captures_total"
_STEP_PEAK_MEM = "dl4j_step_peak_memory_bytes"

# peak dense matmul throughput per chip, bf16 FLOP/s (public spec sheets)
# — the one owner of the table (bench.py imports it from here)
PEAK_FLOPS = {
    "TPU v6": 918e12,
    "TPU v5p": 459e12,
    "TPU v5": 197e12,   # v5 lite (v5e)
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}

# ESTIMATE: one modern server socket sustains O(100) GFLOP/s fp32 through
# a single-threaded-ish XLA:CPU step.  Only order-of-magnitude accurate —
# every consumer labels CPU-derived MFU as an estimate, and MFU is
# clamped to 1.0 (docs/observability.md "MFU definition").
CPU_PEAK_FLOPS_ESTIMATE = 1e11


def peak_flops_for(device=None) -> Tuple[float, str]:
    """(peak FLOP/s, source) for a jax device (default: devices()[0]).
    source: ``"table"`` (spec-sheet TPU number), ``"cpu-estimate"``
    (documented estimate, see ``CPU_PEAK_FLOPS_ESTIMATE``), or
    ``"unknown"`` (0.0 — MFU not computable)."""
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:
            return 0.0, "unknown"
    kind = getattr(device, "device_kind", "") or ""
    for prefix, peak in PEAK_FLOPS.items():
        if kind.startswith(prefix):
            return peak, "table"
    if getattr(device, "platform", "") == "cpu":
        return CPU_PEAK_FLOPS_ESTIMATE, "cpu-estimate"
    return 0.0, "unknown"


# ------------------------------------------------------------ cost analysis
def jit_cost_analysis(fn, args: Tuple, kwargs: Dict) -> Dict[str, float]:
    """XLA cost analysis of ``fn`` (a jitted callable) at the ABSTRACT
    signature of ``args``/``kwargs``: every array leaf is replaced by a
    ``ShapeDtypeStruct`` before lowering (input shardings preserved), so
    the concrete buffers are never touched (safe with donated args) and
    nothing executes.  Returns ``{"flops": ..., "bytes_accessed": ...}``
    or ``{}`` when the backend does not support cost analysis.  Thin
    wrapper over ``shardstats.program_analysis`` — the ONE owner of the
    abstract-lowering recipe."""
    from deeplearning4j_tpu.observability import shardstats

    out = shardstats.program_analysis(fn, args, kwargs, memory=False,
                                      collectives=False)
    if "flops" not in out and "bytes_accessed" not in out:
        return {}
    return {"flops": out.get("flops", 0.0),
            "bytes_accessed": out.get("bytes_accessed", 0.0)}


# -------------------------------------------------------- memory attribution
def _leaf_bytes(leaf) -> int:
    n = getattr(leaf, "nbytes", None)
    if n is not None:
        return int(n)
    return 0


def model_memory_breakdown(net, top: int = 16) -> Dict[str, Any]:
    """Per-leaf byte breakdown of a model facade's params / updater state /
    net state — the "what holds the HBM" answer for a parked model.
    Returns section totals plus the ``top`` largest leaves with their
    tree paths."""
    import jax

    sections = {
        "params": getattr(net, "params", None),
        "updater_state": getattr(net, "updater_state", None),
        "net_state": getattr(net, "net_state", None),
    }
    totals: Dict[str, int] = {}
    leaves: List[Dict[str, Any]] = []
    for section, tree in sections.items():
        total = 0
        if tree:
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
                b = _leaf_bytes(leaf)
                total += b
                leaves.append({
                    "section": section,
                    "path": jax.tree_util.keystr(path),
                    "bytes": b,
                    "shape": list(getattr(leaf, "shape", ()) or ()),
                    "dtype": str(getattr(leaf, "dtype", "")),
                })
        totals[f"{section}_bytes"] = total
    leaves.sort(key=lambda d: d["bytes"], reverse=True)
    return {
        **totals,
        "total_bytes": sum(totals.values()),
        "top_leaves": leaves[:top],
    }


def live_buffer_snapshot(top: int = 20) -> Dict[str, Any]:
    """All live jax arrays in the process, grouped by (shape, dtype) and
    sorted by total bytes — the "what is holding memory RIGHT NOW" view a
    crash/hang dump needs.  Cost is O(live arrays); called at capture and
    dump time, never per step."""
    import jax

    groups: Dict[Tuple, List[int]] = {}
    total = 0
    count = 0
    try:
        arrs = jax.live_arrays()
    except Exception:
        return {"total_bytes": 0, "count": 0, "top": [], "error": "unavailable"}
    for a in arrs:
        b = _leaf_bytes(a)
        total += b
        count += 1
        key = (tuple(getattr(a, "shape", ()) or ()),
               str(getattr(a, "dtype", "")))
        g = groups.setdefault(key, [0, 0])
        g[0] += 1
        g[1] += b
    ranked = sorted(groups.items(), key=lambda kv: kv[1][1], reverse=True)
    return {
        "total_bytes": total,
        "count": count,
        "top": [{"shape": list(shape), "dtype": dtype, "count": n,
                 "bytes": b} for (shape, dtype), (n, b) in ranked[:top]],
    }


def peak_memory_snapshot() -> Dict[str, Any]:
    """Per-device peak allocation (PJRT ``peak_bytes_in_use``); on backends
    without memory stats (CPU) falls back to the live-buffer total, labeled
    as the estimate it is."""
    from deeplearning4j_tpu.observability.memory import device_memory_stats

    stats = device_memory_stats()
    if stats:
        return {"source": "pjrt", "devices": stats,
                "peak_bytes": max((per.get("peak_bytes_in_use") or 0)
                                  for per in stats.values())}
    live = live_buffer_snapshot(top=0)
    return {"source": "live_buffers_estimate",
            "peak_bytes": live["total_bytes"]}


def memory_attribution() -> Dict[str, Any]:
    """The flight-dump memory section: live buffers plus the per-leaf
    breakdown of every model the active profiler tracks."""
    out: Dict[str, Any] = {"live_buffers": live_buffer_snapshot()}
    prof = _active
    if prof is not None:
        models = {}
        for kind, net in prof.tracked_models():
            try:
                models[kind] = model_memory_breakdown(net)
            except Exception as e:
                models[kind] = {"error": repr(e)}
        out["models"] = models
    return out


# --------------------------------------------------------------- profiler
class StepProfiler:
    """On-demand and trigger-driven step capture + MFU attribution.

    Usage::

        prof = StepProfiler("profiles", capture_step=3).install()
        net.fit(batches)          # step 3 is captured; MFU gauges filled
        prof.uninstall()

    or as a context manager (``with StepProfiler(...) as prof:``).

    Capture triggers (each capture is one step wrapped in
    ``jax.profiler.start_trace``/``stop_trace`` + a host-span Chrome-trace
    export, named in a ``profile_capture`` flight event):

    - ``capture_step=N`` / ``capture_steps=(...)``: the step whose
      ``step_guard`` ``iteration`` attr matches;
    - straggler verdict (``capture_on_straggler``): the PR-3
      ``StragglerDetector`` arms a one-shot capture of the next step;
    - watchdog dump (``capture_on_watchdog``): a hang report arms a
      capture of the next step that runs (the hung step itself never
      finishes — the next one shows what the recovered loop does);
    - ``request_capture(reason)``: manual one-shot.

    Disk budget: capture directories under ``profile_dir`` are deleted
    oldest-first once their total size exceeds ``max_disk_bytes`` (the
    newest capture is always kept).

    While installed, every ``instrument``-wrapped jitted function reports
    its per-signature XLA cost analysis through ``note_dispatch`` and the
    ``step_guard`` seam turns that into per-step MFU/roofline gauges —
    see the module docstring for the metric families.
    """

    def __init__(self, profile_dir: str = "profiles", *,
                 capture_step: Optional[int] = None,
                 capture_steps: Tuple[int, ...] = (),
                 capture_on_straggler: bool = True,
                 capture_on_watchdog: bool = True,
                 max_disk_bytes: int = 256 << 20,
                 use_jax_profiler: bool = True,
                 cost_analysis: bool = True,
                 peak_flops: Optional[float] = None,
                 registry=None):
        from deeplearning4j_tpu.observability.metrics import get_registry

        self.profile_dir = str(profile_dir)
        self.capture_step = capture_step
        self.capture_steps = tuple(capture_steps)
        self.capture_on_straggler = capture_on_straggler
        self.capture_on_watchdog = capture_on_watchdog
        self.max_disk_bytes = int(max_disk_bytes)
        self.use_jax_profiler = use_jax_profiler
        self.cost_analysis = cost_analysis
        if peak_flops is not None:
            self.peak_flops, self.peak_source = float(peak_flops), "override"
        else:
            self.peak_flops, self.peak_source = peak_flops_for()
        reg = registry if registry is not None else get_registry()
        self._registry = reg
        self._m_flops = reg.counter(
            _FLOPS, "FLOPs dispatched per jitted function (XLA cost "
            "analysis of the compiled signature, counted once per call)",
            labels=("fn",))
        self._m_mfu = reg.gauge(
            _MFU, "Model FLOPs utilization of the most recent step: step "
            "FLOPs / step seconds / backend peak FLOP/s (clamped to 1.0; "
            "CPU peak is a documented estimate)", labels=("component",))
        self._m_bpf = reg.gauge(
            _BPF, "Roofline position of the most recent step: XLA "
            "bytes-accessed / flops (high = memory-bound)",
            labels=("component",))
        self._m_peak = reg.gauge(
            _PEAK, "Peak FLOP/s assumed for MFU (spec-sheet table for "
            "TPUs; on CPU a documented order-of-magnitude estimate)",
            labels=("source",))
        self._m_caps = reg.counter(
            _CAPTURES, "Profiler trace captures written, by trigger",
            labels=("reason",))
        self._m_peak_mem = reg.gauge(
            _STEP_PEAK_MEM, "Peak device allocation observed at the end "
            "of the most recent step (PJRT peak_bytes_in_use; absent on "
            "backends without memory stats)", labels=("component", "device"))
        self._lock = threading.Lock()
        self._pending: Optional[str] = None
        self._tls = threading.local()
        self._cap_ids = itertools.count(1)
        self._models: "weakref.WeakValueDictionary[str, Any]" = (
            weakref.WeakValueDictionary())
        self.capture_paths: List[str] = []

    # ------------------------------------------------------------ lifecycle
    def install(self) -> "StepProfiler":
        global _active
        self._m_peak.set(self.peak_flops, source=self.peak_source)
        _active = self
        return self

    def uninstall(self) -> None:
        global _active
        if _active is self:
            _active = None

    def __enter__(self) -> "StepProfiler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------------- triggers
    def request_capture(self, reason: str) -> None:
        """Arm a one-shot capture of the NEXT guarded step (thread-safe;
        a second request while one is pending is coalesced)."""
        from deeplearning4j_tpu.observability.flightrecorder import (
            get_flight_recorder,
        )

        with self._lock:
            if self._pending is not None:
                return
            self._pending = str(reason)
        get_flight_recorder().record("profile_requested", reason=reason)

    # -------------------------------------------------------- model tracking
    def track_model(self, net, kind: str) -> None:
        """Weakly register a model facade for memory attribution (fit
        loops call this; a dropped model unregisters itself)."""
        self._models[str(kind)] = net

    def tracked_models(self) -> List[Tuple[str, Any]]:
        return [(k, v) for k, v in self._models.items() if v is not None]

    # ------------------------------------------------------- step_guard seam
    def on_step_begin(self, name: str, attrs: Dict[str, Any]) -> Dict:
        """Called by ``step_guard`` on entry; returns the per-step frame
        that accumulates this step's dispatched cost."""
        reason = None
        with self._lock:
            if self._pending is not None:
                reason, self._pending = self._pending, None
        if reason is None:
            it = attrs.get("iteration")
            if it is not None and (it == self.capture_step
                                   or it in self.capture_steps):
                reason = f"step:{it}"
        frame = {"flops": 0.0, "bytes": 0.0, "capture": None}
        if reason is not None:
            try:
                frame["capture"] = self._begin_capture(name, attrs, reason)
            except Exception:
                frame["capture"] = None
        stack = getattr(self._tls, "frames", None)
        if stack is None:
            stack = self._tls.frames = []
        stack.append(frame)
        return frame

    def note_dispatch(self, fn_name: str, cost: Optional[Dict]) -> None:
        """Called by ``_InstrumentedJit`` per call with the dispatched
        signature's cached cost analysis; accumulates into the innermost
        active step frame on this thread."""
        if not cost:
            return
        flops = float(cost.get("flops") or 0.0)
        nbytes = float(cost.get("bytes_accessed") or 0.0)
        if flops > 0:
            self._m_flops.inc(flops, fn=fn_name)
        stack = getattr(self._tls, "frames", None)
        if stack:
            stack[-1]["flops"] += flops
            stack[-1]["bytes"] += nbytes

    def on_step_end(self, name: str, seconds: float, attrs: Dict[str, Any],
                    frame: Dict, error: Optional[BaseException] = None) -> None:
        stack = getattr(self._tls, "frames", None)
        if stack:
            # remove by IDENTITY: nested frames with equal contents (all
            # zeros before any dispatch) must not evict each other
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is frame:
                    del stack[i]
                    break
        component = (attrs.get("model") or attrs.get("component") or name)
        flops, nbytes = frame["flops"], frame["bytes"]
        mfu = None
        if flops > 0 and seconds > 0:
            if self.peak_flops > 0:
                mfu = min(1.0, flops / seconds / self.peak_flops)
                self._m_mfu.set(mfu, component=component)
            self._m_bpf.set(nbytes / flops, component=component)
        self._sample_step_memory(component)
        cap = frame.get("capture")
        if cap is not None:
            self._finish_capture(cap, name, seconds, attrs, flops, nbytes,
                                 mfu, error)

    def _sample_step_memory(self, component: str) -> None:
        from deeplearning4j_tpu.observability.memory import (
            device_memory_stats,
        )

        try:
            for dev, per in device_memory_stats().items():
                peak = per.get("peak_bytes_in_use")
                if peak is not None:
                    self._m_peak_mem.set(peak, component=component,
                                         device=dev)
        except Exception:
            pass

    # --------------------------------------------------------------- capture
    def _begin_capture(self, name: str, attrs: Dict, reason: str) -> Dict:
        safe = "".join(c if (c.isalnum() or c in "._-") else "-"
                       for c in reason)[:48]
        cap_dir = os.path.join(self.profile_dir,
                               f"cap-{next(self._cap_ids):04d}-{safe}")
        os.makedirs(cap_dir, exist_ok=True)
        cap = {"reason": reason, "dir": cap_dir, "jax_started": False,
               "t0_ns": time.perf_counter_ns()}
        if self.use_jax_profiler:
            try:
                import jax

                jax.profiler.start_trace(cap_dir)
                cap["jax_started"] = True
            except Exception:
                cap["jax_started"] = False
        return cap

    def _finish_capture(self, cap: Dict, name: str, seconds: float,
                        attrs: Dict, flops: float, nbytes: float,
                        mfu: Optional[float],
                        error: Optional[BaseException]) -> None:
        from deeplearning4j_tpu.observability.flightrecorder import (
            get_flight_recorder,
        )
        from deeplearning4j_tpu.observability.tracing import get_tracer

        if cap["jax_started"]:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
        t1_ns = time.perf_counter_ns()
        tracer = get_tracer()
        span_path = os.path.join(cap["dir"], "host_spans.trace.json")
        spans = 0
        try:
            spans = tracer.export_chrome_trace(
                span_path, tracer.spans_between(cap["t0_ns"], t1_ns))
        except Exception:
            span_path = None
        meta = {
            "reason": cap["reason"],
            "step": name,
            "attrs": {k: v for k, v in attrs.items()
                      if isinstance(v, (str, int, float, bool, type(None)))},
            "seconds": seconds,
            "flops": flops,
            "bytes_accessed": nbytes,
            "mfu": mfu,
            "peak_flops": self.peak_flops,
            "peak_flops_source": self.peak_source,
            "host_spans": spans,
            "error": repr(error) if error is not None else None,
            "memory": None,
        }
        try:
            meta["memory"] = {**peak_memory_snapshot(),
                              "live_buffers": live_buffer_snapshot()}
        except Exception:
            pass
        try:
            with open(os.path.join(cap["dir"], "capture.json"), "w") as f:
                json.dump(meta, f, indent=1, default=str)
        except OSError:
            pass
        category = cap["reason"].split(":", 1)[0]
        self._m_caps.inc(reason=category)
        self.capture_paths.append(cap["dir"])
        get_flight_recorder().record(
            "profile_capture", reason=cap["reason"], step=name,
            path=cap["dir"], trace_file=span_path,
            seconds=round(seconds, 6), flops=flops,
            mfu=None if mfu is None else round(mfu, 6))
        self._enforce_budget()

    def _enforce_budget(self) -> None:
        """Delete oldest capture directories once the on-disk total
        exceeds ``max_disk_bytes`` (newest always kept)."""
        try:
            caps = []
            for entry in os.listdir(self.profile_dir):
                path = os.path.join(self.profile_dir, entry)
                if not (entry.startswith("cap-") and os.path.isdir(path)):
                    continue
                size = 0
                for root, _dirs, files in os.walk(path):
                    for fl in files:
                        try:
                            size += os.path.getsize(os.path.join(root, fl))
                        except OSError:
                            pass
                caps.append((os.path.getmtime(path), path, size))
            caps.sort()   # oldest first
            total = sum(s for _, _, s in caps)
            while total > self.max_disk_bytes and len(caps) > 1:
                _, path, size = caps.pop(0)
                shutil.rmtree(path, ignore_errors=True)
                total -= size
        except OSError:
            pass


# ------------------------------------------------------------ module seams
_active: Optional[StepProfiler] = None


def active_profiler() -> Optional[StepProfiler]:
    """The installed profiler, or None (lock-free read: module-global
    assignment is atomic)."""
    return _active


def notify_straggler(component: str, worker: str) -> None:
    """Straggler-verdict hook (called by ``health.StragglerDetector``):
    arms a one-shot capture of the next step so the trace shows what the
    degraded window actually did."""
    prof = _active
    if prof is not None and prof.capture_on_straggler:
        prof.request_capture(f"straggler:{component}:{worker}")


def notify_watchdog(reason: str) -> None:
    """Watchdog-dump hook (called by ``flightrecorder.StepWatchdog``)."""
    prof = _active
    if prof is not None and prof.capture_on_watchdog:
        prof.request_capture(f"watchdog:{reason}")
