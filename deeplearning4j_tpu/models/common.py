"""Shared facade plumbing for MultiLayerNetwork / ComputationGraph.

``LazyScoreMixin`` removes the per-step host sync from every training hot
loop (the reference's score update ``BaseOptimizer.java`` feeds listeners a
host double every iteration; on TPU a per-step ``float(loss)`` blocks step
N+1's dispatch behind step N's execution).  Training loops store the
*on-device* loss scalar; the transfer happens only when somebody actually
reads ``score_value`` — a listener, early stopping, a test — and the fetched
float is cached until the next step overwrites it.
"""

from __future__ import annotations

from typing import Any


class LazyScoreMixin:
    """Lazy ``score_value``: assign device arrays freely, pay the
    device->host sync only on read."""

    _score: Any = None

    @property
    def score_value(self) -> float:
        s = getattr(self, "_score", None)
        if s is None:
            return float("nan")
        if not isinstance(s, float):
            s = float(s)  # device -> host sync happens here, on demand
            self._score = s
        return s

    @score_value.setter
    def score_value(self, value) -> None:
        # accepts a python float OR an on-device scalar (no sync either way)
        self._score = value
