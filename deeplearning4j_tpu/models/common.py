"""Shared facade plumbing for MultiLayerNetwork / ComputationGraph.

``LazyScoreMixin`` removes the per-step host sync from every training hot
loop (the reference's score update ``BaseOptimizer.java`` feeds listeners a
host double every iteration; on TPU a per-step ``float(loss)`` blocks step
N+1's dispatch behind step N's execution).  Training loops store the
*on-device* loss scalar; the transfer happens only when somebody actually
reads ``score_value`` — a listener, early stopping, a test — and the fetched
float is cached until the next step overwrites it.
"""

from __future__ import annotations

from typing import Any


class LazyScoreMixin:
    """Lazy ``score_value``: assign device arrays freely, pay the
    device->host sync only on read."""

    _score: Any = None

    @property
    def score_value(self) -> float:
        s = getattr(self, "_score", None)
        if s is None:
            return float("nan")
        if not isinstance(s, float):
            s = float(s)  # device -> host sync happens here, on demand
            self._score = s
        return s

    @score_value.setter
    def score_value(self, value) -> None:
        # accepts a python float OR an on-device scalar (no sync either way)
        self._score = value


def notify_listeners(model, batch_size=None) -> None:
    """Fire ``iteration_done`` on the model's listeners, first wiring the
    actual minibatch size into any listener that wants it (fixes
    ``PerformanceListener`` reporting no samples/sec unless the user called
    ``set_batch_size`` by hand — the fit loop knows the batch, so it tells
    the listeners).  Also mirrors it as ``model.last_batch_size``."""
    if batch_size is not None:
        model.last_batch_size = int(batch_size)
    for lst in model.listeners:
        if batch_size is not None:
            setter = getattr(lst, "set_batch_size", None)
            if setter is not None:
                setter(int(batch_size))
        lst.iteration_done(model, model.iteration)


def seed_stream_caches(named_layers, rnn_state, batch, compute_dtype):
    """Streaming-cache seeding shared by both facades' ``rnn_time_step``:
    for every (name, layer) with an ``init_cache`` and no existing carry,
    allocate a KV cache in the model's compute dtype.  Returns the carries
    dict (may be empty)."""
    import jax.numpy as jnp

    cache_dtype = jnp.dtype(compute_dtype) if compute_dtype else jnp.float32
    carries = dict(rnn_state) if rnn_state else {}
    for name, layer in named_layers:
        if hasattr(layer, "init_cache") and name not in carries:
            cache = layer.init_cache(int(batch), dtype=cache_dtype)
            if cache is not None:
                carries[name] = cache
    return carries


def check_cache_capacity(carries, t_new: int, pos: int | None = None) -> None:
    """Raise before dispatch when a streamed chunk would overflow any
    attention KV cache — ``dynamic_update_slice`` clamps out-of-range
    writes and would silently relocate keys instead of failing.

    ``pos`` is the facade's host-side stream-position counter; passing it
    keeps this check free of device->host syncs in the decode hot loop
    (all caches advance in lockstep with the streamed input)."""
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer

    def walk(name, c):
        if not isinstance(c, dict):
            return
        if "pos" in c and "k" in c:
            if SelfAttentionLayer.cache_overflow(c, t_new, pos=pos):
                at = pos if pos is not None else int(c["pos"])
                raise ValueError(
                    f"rnn_time_step: streaming past the KV cache of "
                    f"'{name}' (pos={at} + {t_new} > "
                    f"max_cache={c['k'].shape[1]}); raise the layer's "
                    "max_cache or rnn_clear_previous_state()")
        else:
            for k, v in c.items():
                walk(f"{name}.{k}", v)

    for name, c in (carries or {}).items():
        walk(name, c)
