"""Graph vertices — DAG building blocks for ComputationGraph.

Reference: ``nn/graph/vertex/GraphVertex.java:36,113,119`` (doForward/
doBackward SPI) and impls ``nn/graph/vertex/impl/{Layer,ElementWise,Merge,
Subset,Preprocessor,Input}Vertex.java`` + ``impl/rnn/{LastTimeStep,
DuplicateToTimeSeries}Vertex.java``.  Functional redesign: a vertex is a
pure function of its input activations; ``doBackward`` is autodiff.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.inputs import InputType

_VERTEX_REGISTRY: Dict[str, Type["GraphVertex"]] = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def vertex_from_dict(d: Dict[str, Any]) -> "GraphVertex":
    d = dict(d)
    cls = _VERTEX_REGISTRY[d.pop("type")]
    return cls.from_dict(d)


@dataclasses.dataclass(frozen=True)
class GraphVertex:
    def apply(self, inputs: List[jax.Array]) -> jax.Array:
        raise NotImplementedError

    def output_type(self, input_types: List[InputType]) -> InputType:
        raise NotImplementedError

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["type"] = type(self).__name__
        return d

    @classmethod
    def from_dict(cls, d):
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


@register_vertex
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertex):
    """Pointwise combine: add | subtract | product | average | max
    (reference ``ElementWiseVertex.java``; 'add' is the residual-connection
    vertex ResNet uses)."""

    op: str = "add"

    def apply(self, inputs):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            return inputs[0] - inputs[1]
        if op in ("product", "mul"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op in ("average", "avg"):
            return sum(inputs) / len(inputs)
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown elementwise op {self.op}")

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel axis (reference
    ``MergeVertex.java``; inception-style blocks)."""

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=-1)

    def output_type(self, input_types):
        t0 = input_types[0]
        if t0.kind == "cnn":
            return InputType.convolutional(
                t0.height, t0.width, sum(t.channels for t in input_types)
            )
        if t0.kind == "rnn":
            return InputType.recurrent(sum(t.size for t in input_types), t0.timesteps)
        return InputType.feed_forward(sum(t.flat_size() for t in input_types))


@register_vertex
@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertex):
    """Feature-range slice [from, to] inclusive (reference ``SubsetVertex``)."""

    index_from: int = 0
    index_to: int = 0

    def apply(self, inputs):
        return inputs[0][..., self.index_from : self.index_to + 1]

    def output_type(self, input_types):
        n = self.index_to - self.index_from + 1
        t = input_types[0]
        if t.kind == "rnn":
            return InputType.recurrent(n, t.timesteps)
        if t.kind == "cnn":
            return InputType.convolutional(t.height, t.width, n)
        return InputType.feed_forward(n)


@register_vertex
@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertex):
    factor: float = 1.0

    def apply(self, inputs):
        return inputs[0] * self.factor

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertex):
    """[B,T,F] -> [B,F] at the last unmasked step (reference
    ``rnn/LastTimeStepVertex.java``).  With a mask, picks each example's
    final real timestep via one gather."""

    def apply(self, inputs, mask=None):
        x = inputs[0]
        if mask is not None:
            idx = jnp.maximum(jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)
            return jax.vmap(lambda seq, i: seq[i])(x, idx)
        return x[:, -1]

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)


@register_vertex
@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B,F] -> [B,T,F] broadcast over T taken from a reference input
    (reference ``rnn/DuplicateToTimeSeriesVertex.java``)."""

    timesteps: Optional[int] = None

    def apply(self, inputs):
        x = inputs[0]
        T = self.timesteps
        if T is None and len(inputs) > 1:
            T = inputs[1].shape[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], T, x.shape[-1]))

    def output_type(self, input_types):
        return InputType.recurrent(input_types[0].flat_size(), self.timesteps)


@register_vertex
@dataclasses.dataclass(frozen=True)
class PreprocessorVertex(GraphVertex):
    """Wraps an input preprocessor as a standalone vertex."""

    preprocessor: Optional[dict] = None  # serialized Preprocessor

    def _proc(self):
        from deeplearning4j_tpu.nn.preprocessors import preproc_from_dict

        return preproc_from_dict(self.preprocessor)

    def apply(self, inputs):
        return self._proc()(inputs[0])

    def output_type(self, input_types):
        return self._proc().output_type(input_types[0])
