"""Model zoo — the benchmark configs from BASELINE.md built on the DSL.

- LeNet-5 / MNIST  (reference baseline config 1: MultiLayerNetwork)
- ResNet-50        (reference baseline config 2: ComputationGraph; residual
  adds via ElementWiseVertex)
- GravesLSTM char-LM (reference baseline config 3)

All TPU-first: NHWC, bf16-ready, static shapes.
"""

from __future__ import annotations

from typing import Optional, Sequence

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    RBM,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    GravesLSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.models.graph import ComputationGraph, GraphConfiguration
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
from deeplearning4j_tpu.models.vertices import ElementWiseVertex, MergeVertex


def lenet(seed: int = 12345, updater: str = "nesterovs", lr: float = 0.01,
          n_classes: int = 10) -> MultiLayerNetwork:
    """LeNet-5 on 28x28x1 (the classic DL4J MNIST example config)."""
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
        .regularization(True)
        .l2(5e-4)
        .list()
        .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                activation="identity", weight_init="xavier"))
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                activation="identity"))
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=500, activation="relu"))
        .layer(OutputLayer(n_out=n_classes, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.convolutional_flat(28, 28, 1))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _bottleneck(g, name: str, in_name: str, channels: int, stride: int,
                project: bool):
    """ResNet-v1 bottleneck: 1x1 -> 3x3 -> 1x1(4c) + shortcut, post-add relu."""
    mid = channels
    out_ch = channels * 4
    g.add_layer(f"{name}_c1", ConvolutionLayer(
        n_out=mid, kernel_size=(1, 1), stride=(stride, stride),
        activation="identity", weight_init="relu"), in_name)
    g.add_layer(f"{name}_bn1", BatchNormalization(activation="relu"), f"{name}_c1")
    g.add_layer(f"{name}_c2", ConvolutionLayer(
        n_out=mid, kernel_size=(3, 3), stride=(1, 1), padding=(1, 1),
        activation="identity", weight_init="relu"), f"{name}_bn1")
    g.add_layer(f"{name}_bn2", BatchNormalization(activation="relu"), f"{name}_c2")
    g.add_layer(f"{name}_c3", ConvolutionLayer(
        n_out=out_ch, kernel_size=(1, 1), stride=(1, 1),
        activation="identity", weight_init="relu"), f"{name}_bn2")
    g.add_layer(f"{name}_bn3", BatchNormalization(activation="identity"), f"{name}_c3")
    shortcut = in_name
    if project:
        g.add_layer(f"{name}_proj", ConvolutionLayer(
            n_out=out_ch, kernel_size=(1, 1), stride=(stride, stride),
            activation="identity", weight_init="relu"), in_name)
        g.add_layer(f"{name}_projbn", BatchNormalization(activation="identity"),
                    f"{name}_proj")
        shortcut = f"{name}_projbn"
    g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), f"{name}_bn3", shortcut)
    from deeplearning4j_tpu.nn.layers import ActivationLayer

    g.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_relu"


def resnet50(height: int = 224, width: int = 224, channels: int = 3,
             n_classes: int = 1000, seed: int = 12345,
             updater: str = "nesterovs", lr: float = 0.1,
             blocks: Sequence[int] = (3, 4, 6, 3),
             stem_stride: int = 2, init_channels: int = 64,
             compute_dtype: Optional[str] = None) -> ComputationGraph:
    """ResNet-50 as a ComputationGraph (residual adds = ElementWiseVertex,
    the reference's DAG capability exercised at benchmark scale).

    For CIFAR-scale inputs pass height=width=32, stem_stride=1."""
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
        .graph()
        .add_inputs("input")
        .set_input_types(input=InputType.convolutional(height, width, channels))
    )
    if compute_dtype:
        b.compute_dtype(compute_dtype)
    stem_kernel = (7, 7) if stem_stride == 2 else (3, 3)
    stem_pad = (3, 3) if stem_stride == 2 else (1, 1)
    b.add_layer("stem", ConvolutionLayer(
        n_out=init_channels, kernel_size=stem_kernel,
        stride=(stem_stride, stem_stride), padding=stem_pad,
        activation="identity", weight_init="relu"), "input")
    b.add_layer("stem_bn", BatchNormalization(activation="relu"), "stem")
    prev = "stem_bn"
    if stem_stride == 2:
        b.add_layer("stem_pool", SubsamplingLayer(
            pooling_type="max", kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)),
            "stem_bn")
        prev = "stem_pool"
    ch = init_channels
    for stage, n_blocks in enumerate(blocks):
        for i in range(n_blocks):
            stride = 2 if (stage > 0 and i == 0) else 1
            project = i == 0
            prev = _bottleneck(b, f"s{stage}b{i}", prev, ch, stride, project)
        ch *= 2
    b.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), prev)
    b.add_layer("fc", OutputLayer(n_out=n_classes, loss="mcxent",
                                  activation="softmax", weight_init="xavier"), "gap")
    conf = b.set_outputs("fc").build()
    return ComputationGraph(conf).init()


def alexnet(height: int = 224, width: int = 224, channels: int = 3,
            n_classes: int = 1000, seed: int = 12345,
            updater: str = "nesterovs", lr: float = 0.01,
            compute_dtype: Optional[str] = None) -> MultiLayerNetwork:
    """AlexNet (the classic DL4J model-zoo config: 5 conv + LRN + 3 fc with
    dropout).  Exercises LRN (the Pallas helper path) at benchmark scale."""
    from deeplearning4j_tpu.nn.layers import LocalResponseNormalization

    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater, learning_rate=lr)
         .regularization(True).l2(5e-4).list())
    if compute_dtype:
        b.compute_dtype(compute_dtype)
    (b.layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11), stride=(4, 4),
                              activation="relu", weight_init="relu"))
      .layer(LocalResponseNormalization())
      .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2)))
      .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5), stride=(1, 1),
                              padding=(2, 2), activation="relu"))
      .layer(LocalResponseNormalization())
      .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2)))
      .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3), stride=(1, 1),
                              padding=(1, 1), activation="relu"))
      .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3), stride=(1, 1),
                              padding=(1, 1), activation="relu"))
      .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3), stride=(1, 1),
                              padding=(1, 1), activation="relu"))
      .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2)))
      .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
      .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
      .layer(OutputLayer(n_out=n_classes, loss="mcxent", activation="softmax"))
      .set_input_type(InputType.convolutional(height, width, channels)))
    return MultiLayerNetwork(b.build()).init()


def vgg16(height: int = 224, width: int = 224, channels: int = 3,
          n_classes: int = 1000, seed: int = 12345,
          updater: str = "nesterovs", lr: float = 0.01,
          compute_dtype: Optional[str] = None) -> MultiLayerNetwork:
    """VGG-16 (13 conv 3x3 + 3 fc; DL4J model-zoo config)."""
    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(updater, learning_rate=lr)
         .regularization(True).l2(5e-4).list())
    if compute_dtype:
        b.compute_dtype(compute_dtype)
    for block, (n_convs, ch) in enumerate([(2, 64), (2, 128), (3, 256),
                                           (3, 512), (3, 512)]):
        for _ in range(n_convs):
            b.layer(ConvolutionLayer(n_out=ch, kernel_size=(3, 3),
                                     stride=(1, 1), padding=(1, 1),
                                     activation="relu"))
        b.layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                 stride=(2, 2)))
    (b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
      .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
      .layer(OutputLayer(n_out=n_classes, loss="mcxent", activation="softmax"))
      .set_input_type(InputType.convolutional(height, width, channels)))
    return MultiLayerNetwork(b.build()).init()


def _inception(g, name: str, in_name: str, c1: int, c3r: int, c3: int,
               c5r: int, c5: int, cp: int) -> str:
    """GoogLeNet inception module: four parallel branches (1x1 | 1x1->3x3 |
    1x1->5x5 | maxpool->1x1) channel-concatenated via MergeVertex."""
    g.add_layer(f"{name}_b1", ConvolutionLayer(
        n_out=c1, kernel_size=(1, 1), activation="relu", weight_init="relu"),
        in_name)
    g.add_layer(f"{name}_b2r", ConvolutionLayer(
        n_out=c3r, kernel_size=(1, 1), activation="relu", weight_init="relu"),
        in_name)
    g.add_layer(f"{name}_b2", ConvolutionLayer(
        n_out=c3, kernel_size=(3, 3), padding=(1, 1), activation="relu",
        weight_init="relu"), f"{name}_b2r")
    g.add_layer(f"{name}_b3r", ConvolutionLayer(
        n_out=c5r, kernel_size=(1, 1), activation="relu", weight_init="relu"),
        in_name)
    g.add_layer(f"{name}_b3", ConvolutionLayer(
        n_out=c5, kernel_size=(5, 5), padding=(2, 2), activation="relu",
        weight_init="relu"), f"{name}_b3r")
    g.add_layer(f"{name}_b4p", SubsamplingLayer(
        pooling_type="max", kernel_size=(3, 3), stride=(1, 1), padding=(1, 1)),
        in_name)
    g.add_layer(f"{name}_b4", ConvolutionLayer(
        n_out=cp, kernel_size=(1, 1), activation="relu", weight_init="relu"),
        f"{name}_b4p")
    g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_b1", f"{name}_b2",
                 f"{name}_b3", f"{name}_b4")
    return f"{name}_cat"


def googlenet(height: int = 224, width: int = 224, channels: int = 3,
              n_classes: int = 1000, seed: int = 12345,
              updater: str = "nesterovs", lr: float = 0.01,
              compute_dtype: Optional[str] = None) -> ComputationGraph:
    """GoogLeNet / Inception-v1 as a ComputationGraph — the era model whose
    parallel-branch modules exercise MergeVertex channel concatenation at
    benchmark scale (the reference's DAG merge capability,
    ``nn/graph/vertex/impl/MergeVertex.java``)."""
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
        .regularization(True)
        .l2(2e-4)
        .graph()
        .add_inputs("input")
        .set_input_types(input=InputType.convolutional(height, width, channels))
    )
    if compute_dtype:
        b.compute_dtype(compute_dtype)
    b.add_layer("stem1", ConvolutionLayer(
        n_out=64, kernel_size=(7, 7), stride=(2, 2), padding=(3, 3),
        activation="relu", weight_init="relu"), "input")
    b.add_layer("pool1", SubsamplingLayer(
        pooling_type="max", kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)),
        "stem1")
    b.add_layer("stem2r", ConvolutionLayer(
        n_out=64, kernel_size=(1, 1), activation="relu", weight_init="relu"),
        "pool1")
    b.add_layer("stem2", ConvolutionLayer(
        n_out=192, kernel_size=(3, 3), padding=(1, 1), activation="relu",
        weight_init="relu"), "stem2r")
    b.add_layer("pool2", SubsamplingLayer(
        pooling_type="max", kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)),
        "stem2")
    # (c1, c3r, c3, c5r, c5, cp) per module — the published v1 table
    prev = _inception(b, "i3a", "pool2", 64, 96, 128, 16, 32, 32)
    prev = _inception(b, "i3b", prev, 128, 128, 192, 32, 96, 64)
    b.add_layer("pool3", SubsamplingLayer(
        pooling_type="max", kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)),
        prev)
    prev = _inception(b, "i4a", "pool3", 192, 96, 208, 16, 48, 64)
    prev = _inception(b, "i4b", prev, 160, 112, 224, 24, 64, 64)
    prev = _inception(b, "i4c", prev, 128, 128, 256, 24, 64, 64)
    prev = _inception(b, "i4d", prev, 112, 144, 288, 32, 64, 64)
    prev = _inception(b, "i4e", prev, 256, 160, 320, 32, 128, 128)
    b.add_layer("pool4", SubsamplingLayer(
        pooling_type="max", kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)),
        prev)
    prev = _inception(b, "i5a", "pool4", 256, 160, 320, 32, 128, 128)
    prev = _inception(b, "i5b", prev, 384, 192, 384, 48, 128, 128)
    b.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), prev)
    b.add_layer("fc", OutputLayer(n_out=n_classes, loss="mcxent",
                                  activation="softmax", weight_init="xavier",
                                  dropout=0.4), "gap")
    conf = b.set_outputs("fc").build()
    return ComputationGraph(conf).init()


def dbn(n_in: int = 784, hidden: Sequence[int] = (500, 250, 100),
        n_classes: int = 10, seed: int = 12345, updater: str = "nesterovs",
        lr: float = 0.1, k: int = 1) -> MultiLayerNetwork:
    """Deep Belief Network — stacked RBMs + softmax output, trained by
    layerwise CD-k ``pretrain`` then supervised ``fit`` (the reference's
    historical flagship workflow: RBM contrastive divergence
    ``nn/layers/feedforward/rbm/RBM.java:66,99`` under
    ``MultiLayerNetwork.pretrain`` ``MultiLayerNetwork.java:164``)."""
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
        .list()
    )
    prev = n_in
    for li, h in enumerate(hidden):
        # first RBM sees real-valued inputs (gaussian visible); deeper ones
        # see sigmoid activations in [0,1] (binary visible)
        b.layer(RBM(n_in=prev, n_out=h, hidden_unit="binary",
                    visible_unit="gaussian" if li == 0 else "binary", k=k))
        prev = h
    b.layer(OutputLayer(n_in=prev, n_out=n_classes, loss="mcxent",
                        activation="softmax"))
    return MultiLayerNetwork(b.build()).init()


def graves_lstm_char_lm(vocab_size: int = 77, hidden: int = 200,
                        seq_len: int = 64, layers: int = 2,
                        seed: int = 12345, updater: str = "rmsprop",
                        lr: float = 0.1, tbptt: int = 50) -> MultiLayerNetwork:
    """GravesLSTM character language model (the classic DL4J char-RNN
    example shape; reference recurrent benchmark config)."""
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
        .list()
    )
    n_in = vocab_size
    for i in range(layers):
        b.layer(GravesLSTM(n_in=n_in, n_out=hidden, activation="tanh"))
        n_in = hidden
    b.layer(RnnOutputLayer(n_in=hidden, n_out=vocab_size, loss="mcxent",
                           activation="softmax"))
    conf = b.backprop_type("truncated_bptt", fwd_length=tbptt, back_length=tbptt).build()
    return MultiLayerNetwork(conf).init()


def transformer_char_lm(vocab_size: int = 77, d_model: int = 128,
                        n_heads: int = 4, layers: int = 2,
                        ff_mult: int = 4, seed: int = 12345,
                        updater: str = "adam", lr: float = 1e-3,
                        seq_axis: Optional[str] = None,
                        remat: bool = False,
                        compute_dtype: Optional[str] = None,
                        rope: bool = True,
                        n_kv_heads: Optional[int] = None,
                        window: Optional[int] = None,
                        max_cache: int = 1024,
                        stability=None,
                        introspection=None,
                        numerics=None) -> MultiLayerNetwork:
    """Causal transformer char-LM — the long-context flagship (no reference
    analog: the reference is pre-transformer, SURVEY.md §5).  With
    ``seq_axis='seq'`` every attention layer runs ring attention over the
    mesh sequence axis (see ``parallel.sequence_parallel``): train
    sequences sharded over chips without materializing full K/V.  With
    ``remat=True`` each block rematerializes its activations in the
    backward pass (jax.checkpoint) — the other half of the long-context
    memory budget.

    ``rope=True`` (default since 2026-07-30) adds rotary position
    embeddings on q/k — parameter-free, so checkpoints are shape-
    compatible either way, but logits differ: models SAVED with the
    earlier position-free config reload exactly (the zip carries
    ``rope`` in the layer config, absent -> False); only params-only
    reloads through this builder must pass ``rope=False`` explicitly."""
    from deeplearning4j_tpu.nn.layers import (
        EmbeddingLayer, LayerNorm, ResidualBlock, SelfAttentionLayer,
    )

    nb = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater, learning_rate=lr)
    )
    if stability is not None:
        # training-stability engine (nn.conf.TrainingStability): the
        # non-finite guard + loss scaling the production loops run with
        nb.training_stability(stability)
    if introspection is not None:
        # training-introspection engine (nn.conf.TrainingIntrospection):
        # per-layer gradient/update/activation stats inside the step
        nb.training_introspection(introspection)
    if numerics is not None:
        # precision-ledger engine (nn.conf.TrainingNumerics): per-layer
        # dynamic-range / format-safety stats inside the step
        nb.training_numerics(numerics)
    b = nb.list()
    if compute_dtype:
        b.compute_dtype(compute_dtype)
    # collapse_column off: ids are [B, T] sequences; a length-1 prompt must
    # keep its time axis (see EmbeddingLayer.collapse_column)
    b.layer(EmbeddingLayer(n_in=vocab_size, n_out=d_model,
                           collapse_column=False))
    for i in range(layers):
        b.layer(ResidualBlock(remat=remat, layers=(
            LayerNorm(n_in=d_model),
            SelfAttentionLayer(n_in=d_model, n_out=d_model,
                               n_heads=n_heads, causal=True,
                               seq_axis=seq_axis, rope=rope,
                               n_kv_heads=n_kv_heads, window=window,
                               max_cache=max_cache),
        )))
        b.layer(ResidualBlock(remat=remat, layers=(
            LayerNorm(n_in=d_model),
            DenseLayer(n_in=d_model, n_out=d_model * ff_mult, activation="relu"),
            DenseLayer(n_in=d_model * ff_mult, n_out=d_model, activation="identity"),
        )))
    b.layer(LayerNorm(n_in=d_model))
    b.layer(RnnOutputLayer(n_in=d_model, n_out=vocab_size, loss="mcxent",
                           activation="softmax"))
    return MultiLayerNetwork(b.build()).init()
