"""On-device autoregressive generation: prefill + ``lax.scan`` decode.

``utils.sampling.sample_sequence`` mirrors the reference's host-side
sampling loop (the DL4J GravesLSTM example's ``sampleCharactersFromNetwork``
over ``rnnTimeStep``) — one dispatch per token, which on a tunneled TPU is
dominated by round-trip latency.  This module is the TPU-native fast path:
the whole generation — prompt prefill, per-token forward through the KV
caches / recurrent carries, logit filtering, and the categorical draw — is
ONE jitted XLA program, with the token loop as ``lax.scan``.  Decode cost
is then what the hardware actually charges: streaming the KV cache through
HBM (the bandwidth GQA and rolling-window caches exist to shrink).

Works for both model families exactly like ``rnn_time_step``: attention
layers carry KV caches, recurrent layers carry hidden state.
``MultiLayerNetwork`` and single-input/single-output ``ComputationGraph``
both compile (reference streaming inference
``MultiLayerNetwork.rnnTimeStep`` :2195 and
``ComputationGraph.rnnTimeStep`` :1674); multi-input graphs keep the host
loop (``utils.sampling.sample_sequence``) — generation feeds back ONE
token stream, so a single input is the only well-defined case.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# the ONE sampling-policy implementation, shared with the host loop and
# the continuous-batching generation engine (utils.sampling owns it so
# temperature/top-k/top-p can never diverge across the decode paths)
from deeplearning4j_tpu.utils.sampling import _sampler  # noqa: F401


def _last_logits_fwd(net):
    """(params, net_state, x, carries) -> (preoutput, new_carries) for
    either model family — the one seam the decode scan needs."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork

    if isinstance(net, MultiLayerNetwork):
        def fwd(params, net_state, x, carries):
            pre, _, _, new_carries = net._forward(
                params, net_state, x, train=False, rng=None,
                carries=carries or None)
            return pre, new_carries
        return fwd

    in_name, out_name = _cg_single_io(net)

    def fwd(params, net_state, x, carries):
        acts, _, new_carries = net._forward(
            params, net_state, {in_name: x}, train=False, rng=None,
            carries=carries or None)
        return acts[out_name], new_carries

    return fwd


def _cg_single_io(net):
    """The single input/output names of a generation-capable graph."""
    if len(net.conf.inputs) != 1 or len(net.conf.outputs) != 1:
        raise ValueError(
            "compiled decode needs a single-input single-output "
            f"ComputationGraph (got {len(net.conf.inputs)} inputs, "
            f"{len(net.conf.outputs)} outputs); use "
            "utils.sampling.sample_sequence for multi-stream graphs")
    return net.conf.inputs[0], net.conf.outputs[0]


def _ids_need_time_axis(net, one_hot: bool) -> bool:
    """True when id inputs must carry a trailing singleton axis so a
    ``collapse_column`` EmbeddingLayer reads [B, T, 1] as T column steps —
    without it a [B, 1] per-token feed collapses to a rank-2 column embed
    and the time axis is lost (``rnn_time_step`` does the same expansion:
    sequential.py / graph.py id rules)."""
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers.dense import EmbeddingLayer

    if one_hot:
        return False
    if isinstance(net, MultiLayerNetwork):
        l0 = net.layers[0] if net.layers else None
        return isinstance(l0, EmbeddingLayer) and l0.collapse_column
    emb = net._id_consumer(_cg_single_io(net)[0])
    return emb is not None and emb.collapse_column


def build_decode_fn(net, steps: int, *, temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    one_hot: bool = False,
                    vocab_size: Optional[int] = None,
                    expand_ids: Optional[bool] = None):
    """Pure generation function for ``net`` (jit it once, call many times).

    Returns ``fn(params, net_state, carries, prompt, rng) -> (ids, carries)``
    where ``prompt`` is [B, T_prompt] int ids, ``carries`` are freshly
    seeded streaming caches (see ``models.common.seed_stream_caches``; may
    be ``{}`` for purely recurrent nets), and ``ids`` is the [B, steps]
    sampled continuation.  The first token is drawn from the prompt's last
    logits; each subsequent token from its predecessor's logits.

    Returned-carries contract: the caches reflect the prompt plus the first
    ``steps - 1`` sampled tokens — the FINAL sampled token is never fed back
    (its logits are never needed), for every ``steps`` including 1.  A
    caller resuming generation from the returned carries must therefore
    feed ``ids[:, -1]`` as the next input; total cache occupancy after a
    call is ``t_prompt + steps - 1`` positions.
    """
    if steps < 1:
        raise ValueError(f"steps={steps} must be >= 1")
    if one_hot and vocab_size is None:
        raise ValueError("one_hot decoding needs vocab_size")
    if expand_ids is None:
        expand_ids = _ids_need_time_axis(net, one_hot)
    sample = _sampler(temperature, top_k, top_p)

    def encode(tok):
        # tok: [B] ids -> one network step of input
        if one_hot:
            return jax.nn.one_hot(tok, vocab_size, dtype=jnp.float32)[:, None]
        # collapse_column embeddings read [B, 1, 1] as one timestep column
        return tok[:, None, None] if expand_ids else tok[:, None]

    fwd = _last_logits_fwd(net)

    def fn(params, net_state, carries, prompt, rng):
        if one_hot:
            x = jax.nn.one_hot(prompt, vocab_size, dtype=jnp.float32)
        else:
            x = prompt[..., None] if expand_ids else prompt
        pre, carries = fwd(params, net_state, x, carries)
        logits0 = pre[:, -1].astype(jnp.float32)
        keys = jax.random.split(rng, steps)
        tok0 = sample(logits0, keys[0])

        def step(carry, key):
            tok, carries = carry
            pre, carries = fwd(params, net_state, encode(tok), carries)
            tok = sample(pre[:, -1].astype(jnp.float32), key)
            return (tok, carries), tok

        if steps == 1:
            return tok0[:, None], carries
        (_, carries), rest = lax.scan(step, (tok0, carries), keys[1:])
        ids = jnp.concatenate([tok0[None], rest], axis=0)   # [steps, B]
        return jnp.transpose(ids), carries

    return fn


def generate(net, prompt_ids, steps: int, *, temperature: float = 1.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             rng: Optional[jax.Array] = None,
             one_hot: Optional[bool] = None,
             vocab_size: Optional[int] = None) -> np.ndarray:
    """Generate ``steps`` tokens after ``prompt_ids`` — same contract as
    ``utils.sampling.sample_sequence`` but compiled end-to-end (the whole
    loop is one XLA program; per-token Python dispatch is gone).  Accepts
    a ``MultiLayerNetwork`` or a single-input/single-output
    ``ComputationGraph`` (multi-stream graphs: use the host loop).

    The decode function is cached on the net per (steps, sampling policy,
    prompt shape), so repeated calls skip retracing.
    """
    from deeplearning4j_tpu.models.common import (
        check_cache_capacity, seed_stream_caches,
    )
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.utils.sampling import _resolve_encoding

    if isinstance(net, MultiLayerNetwork):
        named_layers = [(l.name, l) for l in net.layers]
    else:
        _cg_single_io(net)  # generation feeds back ONE token stream
        named_layers = [(n, net.nodes[n].layer) for n in net.topo
                        if net.nodes[n].layer is not None]
    prompt_ids, one_hot, vocab_size = _resolve_encoding(
        net, prompt_ids, one_hot, vocab_size)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    b, t_prompt = prompt_ids.shape
    carries = seed_stream_caches(named_layers, {}, b,
                                 net.conf.compute_dtype)
    # the WHOLE generation must fit the linear caches; checked host-side
    # once — no per-token position sync (rolling caches never overflow).
    # Occupancy is t_prompt + steps - 1: the final sampled token is never
    # fed back through the cache (see build_decode_fn's carries contract).
    check_cache_capacity(carries, t_prompt + steps - 1, pos=0)

    key = ("decode", steps, temperature, top_k, top_p, one_hot, vocab_size,
           b, t_prompt)
    jitted = net._jit_cache.get(key)
    if jitted is None:
        # carries (arg 2) are freshly seeded per call and discarded after:
        # donating lets XLA write the KV caches in place from the start
        # instead of copying the zero-seeded buffers (cache-sized saving
        # at TPU decode configs)
        jitted = jax.jit(build_decode_fn(
            net, steps, temperature=temperature, top_k=top_k, top_p=top_p,
            one_hot=one_hot, vocab_size=vocab_size), donate_argnums=(2,))
        net._jit_cache[key] = jitted
    ids, _ = jitted(net.params, net.net_state, carries,
                    jnp.asarray(prompt_ids), rng)
    return np.asarray(ids)
