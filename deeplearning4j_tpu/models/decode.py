"""On-device autoregressive generation: prefill + ``lax.scan`` decode.

``utils.sampling.sample_sequence`` mirrors the reference's host-side
sampling loop (the DL4J GravesLSTM example's ``sampleCharactersFromNetwork``
over ``rnnTimeStep``) — one dispatch per token, which on a tunneled TPU is
dominated by round-trip latency.  This module is the TPU-native fast path:
the whole generation — prompt prefill, per-token forward through the KV
caches / recurrent carries, logit filtering, and the categorical draw — is
ONE jitted XLA program, with the token loop as ``lax.scan``.  Decode cost
is then what the hardware actually charges: streaming the KV cache through
HBM (the bandwidth GQA and rolling-window caches exist to shrink).

Works for both model families exactly like ``rnn_time_step``: attention
layers carry KV caches, recurrent layers carry hidden state.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.utils.sampling import _filter_logits


def _sampler(temperature: float, top_k: Optional[int], top_p: Optional[float]):
    """Static sampling policy -> pure (logits [B, V], key) -> ids [B]."""
    if temperature and temperature > 0:

        def sample(logits, key):
            logits = logits / jnp.asarray(temperature, logits.dtype)
            return jax.random.categorical(
                key, _filter_logits(logits, top_k, top_p), axis=-1)
    else:

        def sample(logits, key):
            return jnp.argmax(logits, axis=-1)

    return sample


def build_decode_fn(net, steps: int, *, temperature: float = 1.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    one_hot: bool = False,
                    vocab_size: Optional[int] = None):
    """Pure generation function for ``net`` (jit it once, call many times).

    Returns ``fn(params, net_state, carries, prompt, rng) -> (ids, carries)``
    where ``prompt`` is [B, T_prompt] int ids, ``carries`` are freshly
    seeded streaming caches (see ``models.common.seed_stream_caches``; may
    be ``{}`` for purely recurrent nets), and ``ids`` is the [B, steps]
    sampled continuation.  The first token is drawn from the prompt's last
    logits; each subsequent token from its predecessor's logits.

    Returned-carries contract: the caches reflect the prompt plus the first
    ``steps - 1`` sampled tokens — the FINAL sampled token is never fed back
    (its logits are never needed), for every ``steps`` including 1.  A
    caller resuming generation from the returned carries must therefore
    feed ``ids[:, -1]`` as the next input; total cache occupancy after a
    call is ``t_prompt + steps - 1`` positions.
    """
    if steps < 1:
        raise ValueError(f"steps={steps} must be >= 1")
    if one_hot and vocab_size is None:
        raise ValueError("one_hot decoding needs vocab_size")
    sample = _sampler(temperature, top_k, top_p)

    def encode(tok):
        # tok: [B] ids -> one network step of input
        if one_hot:
            return jax.nn.one_hot(tok, vocab_size, dtype=jnp.float32)[:, None]
        return tok[:, None]

    def fn(params, net_state, carries, prompt, rng):
        x = (jax.nn.one_hot(prompt, vocab_size, dtype=jnp.float32)
             if one_hot else prompt)
        pre, _, _, carries = net._forward(
            params, net_state, x, train=False, rng=None,
            carries=carries or None)
        logits0 = pre[:, -1].astype(jnp.float32)
        keys = jax.random.split(rng, steps)
        tok0 = sample(logits0, keys[0])

        def step(carry, key):
            tok, carries = carry
            pre, _, _, carries = net._forward(
                params, net_state, encode(tok), train=False, rng=None,
                carries=carries)
            tok = sample(pre[:, -1].astype(jnp.float32), key)
            return (tok, carries), tok

        if steps == 1:
            return tok0[:, None], carries
        (_, carries), rest = lax.scan(step, (tok0, carries), keys[1:])
        ids = jnp.concatenate([tok0[None], rest], axis=0)   # [steps, B]
        return jnp.transpose(ids), carries

    return fn


def generate(net, prompt_ids, steps: int, *, temperature: float = 1.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             rng: Optional[jax.Array] = None,
             one_hot: Optional[bool] = None,
             vocab_size: Optional[int] = None) -> np.ndarray:
    """Generate ``steps`` tokens after ``prompt_ids`` — same contract as
    ``utils.sampling.sample_sequence`` but compiled end-to-end (the whole
    loop is one XLA program; per-token Python dispatch is gone).

    The decode function is cached on the net per (steps, sampling policy,
    prompt shape), so repeated calls skip retracing.
    """
    from deeplearning4j_tpu.models.common import (
        check_cache_capacity, seed_stream_caches,
    )
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.utils.sampling import _resolve_encoding

    if not isinstance(net, MultiLayerNetwork):
        raise ValueError(
            "generate() compiles MultiLayerNetwork._forward into the decode "
            "scan; for a ComputationGraph use "
            "utils.sampling.sample_sequence (host streaming loop)")
    prompt_ids, one_hot, vocab_size = _resolve_encoding(
        net, prompt_ids, one_hot, vocab_size)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    b, t_prompt = prompt_ids.shape
    carries = seed_stream_caches(
        ((l.name, l) for l in net.layers), {}, b, net.conf.compute_dtype)
    # the WHOLE generation must fit the linear caches; checked host-side
    # once — no per-token position sync (rolling caches never overflow).
    # Occupancy is t_prompt + steps - 1: the final sampled token is never
    # fed back through the cache (see build_decode_fn's carries contract).
    check_cache_capacity(carries, t_prompt + steps - 1, pos=0)

    key = ("decode", steps, temperature, top_k, top_p, one_hot, vocab_size,
           b, t_prompt)
    jitted = net._jit_cache.get(key)
    if jitted is None:
        jitted = jax.jit(build_decode_fn(
            net, steps, temperature=temperature, top_k=top_k, top_p=top_p,
            one_hot=one_hot, vocab_size=vocab_size))
        net._jit_cache[key] = jitted
    ids, _ = jitted(net.params, net.net_state, carries,
                    jnp.asarray(prompt_ids), rng)
    return np.asarray(ids)
