"""ComputationGraph — the DAG-network facade.

Reference: ``nn/graph/ComputationGraph.java:89-103`` (vertices + topological
order), ``:599-747`` (fit), ``:1012-1036`` (output), ``:1088``
(calcBackpropGradients), builder ``nn/conf/ComputationGraphConfiguration.java:379``
(GraphBuilder) and ``:211`` (validate).

Functional redesign: the graph is data (names, edges, vertex configs);
forward is a pure fold over the topological order; backprop through the DAG
(the reference's hand-routed epsilon fan-out across Merge/ElementWise/Subset
vertices) is ``jax.grad``.  One jitted train step, multi-input multi-output.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.backend.rng import KeyStream
from deeplearning4j_tpu.models.common import LazyScoreMixin, notify_listeners
from deeplearning4j_tpu.observability import (
    crash_dump, fit_telemetry, instrument, step_guard,
)
from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn.conf import (
    TrainingIntrospection, TrainingNumerics, TrainingStability, UpdaterConfig,
)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.layers.dense import OutputLayer
from deeplearning4j_tpu.models.vertices import (
    GraphVertex,
    LastTimeStepVertex,
    vertex_from_dict,
)


@dataclasses.dataclass(frozen=True)
class GraphNode:
    name: str
    inputs: Tuple[str, ...]
    layer: Optional[Layer] = None          # LayerVertex
    vertex: Optional[GraphVertex] = None   # function vertex

    def to_dict(self):
        return {
            "name": self.name,
            "inputs": list(self.inputs),
            "layer": self.layer.to_dict() if self.layer else None,
            "vertex": self.vertex.to_dict() if self.vertex else None,
        }

    @staticmethod
    def from_dict(d):
        return GraphNode(
            name=d["name"],
            inputs=tuple(d["inputs"]),
            layer=layer_from_dict(d["layer"]) if d.get("layer") else None,
            vertex=vertex_from_dict(d["vertex"]) if d.get("vertex") else None,
        )


@dataclasses.dataclass(frozen=True)
class GraphConfiguration:
    inputs: Tuple[str, ...]
    outputs: Tuple[str, ...]
    nodes: Tuple[GraphNode, ...]           # in insertion order
    updater: UpdaterConfig
    input_types: Optional[Dict[str, dict]] = None
    seed: int = 12345
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    optimization_algo: str = "stochastic_gradient_descent"
    num_iterations: int = 1
    compute_dtype: Optional[str] = None  # mixed precision, as MLN conf
    # training-stability engine (nn.conf.TrainingStability), as MLN conf
    stability: Optional[Any] = None
    # training-introspection engine (nn.conf.TrainingIntrospection)
    introspection: Optional[Any] = None
    # precision-ledger engine (nn.conf.TrainingNumerics)
    numerics: Optional[Any] = None

    def topological_order(self) -> List[str]:
        """Kahn's algorithm over the DAG (reference
        ``ComputationGraph.topologicalSortOrder`` :780)."""
        indeg = {n.name: 0 for n in self.nodes}
        children: Dict[str, List[str]] = {name: [] for name in list(self.inputs) + [n.name for n in self.nodes]}
        for n in self.nodes:
            for inp in n.inputs:
                if inp not in children:
                    raise ValueError(f"Vertex '{n.name}' references unknown input '{inp}'")
                children[inp].append(n.name)
                if inp not in self.inputs:
                    indeg[n.name] += 1
        order, queue = [], [n.name for n in self.nodes if indeg[n.name] == 0]
        while queue:
            v = queue.pop(0)
            order.append(v)
            for c in children.get(v, []):
                indeg[c] -= 1
                if indeg[c] == 0:
                    queue.append(c)
        if len(order) != len(self.nodes):
            raise ValueError("Graph has a cycle")
        return order

    def validate(self):
        by_name = {n.name: n for n in self.nodes}
        for out in self.outputs:
            if out not in by_name:
                raise ValueError(f"Output '{out}' is not a vertex")
            node = by_name[out]
            if node.layer is None or not isinstance(node.layer, OutputLayer):
                raise ValueError(
                    f"Output '{out}' must be an OutputLayer/RnnOutputLayer "
                    f"(got {type(node.vertex or node.layer).__name__})"
                )
        self.topological_order()

    def to_yaml(self) -> str:
        """YAML form (reference ComputationGraphConfiguration YAML mapper)."""
        import yaml

        return yaml.safe_dump(json.loads(self.to_json()), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "GraphConfiguration":
        import yaml

        return GraphConfiguration.from_json(json.dumps(yaml.safe_load(s)))

    def to_json(self) -> str:
        return json.dumps(
            {
                "format_version": 1,
                "inputs": list(self.inputs),
                "outputs": list(self.outputs),
                "nodes": [n.to_dict() for n in self.nodes],
                "updater": self.updater.to_dict(),
                "input_types": self.input_types,
                "seed": self.seed,
                "backprop_type": self.backprop_type,
                "tbptt_fwd_length": self.tbptt_fwd_length,
                "tbptt_back_length": self.tbptt_back_length,
                "optimization_algo": self.optimization_algo,
                "num_iterations": self.num_iterations,
                "compute_dtype": self.compute_dtype,
                "stability": (self.stability.to_dict()
                              if self.stability else None),
                "introspection": (self.introspection.to_dict()
                                  if self.introspection else None),
                "numerics": (self.numerics.to_dict()
                             if self.numerics else None),
            },
            indent=2,
        )

    @staticmethod
    def from_json(s: str) -> "GraphConfiguration":
        d = json.loads(s)
        return GraphConfiguration(
            inputs=tuple(d["inputs"]),
            outputs=tuple(d["outputs"]),
            nodes=tuple(GraphNode.from_dict(nd) for nd in d["nodes"]),
            updater=UpdaterConfig.from_dict(d["updater"]),
            input_types=d.get("input_types"),
            seed=d["seed"],
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            optimization_algo=d.get("optimization_algo", "stochastic_gradient_descent"),
            num_iterations=d.get("num_iterations", 1),
            compute_dtype=d.get("compute_dtype"),
            stability=(TrainingStability.from_dict(d["stability"])
                       if d.get("stability") else None),
            introspection=(TrainingIntrospection.from_dict(d["introspection"])
                           if d.get("introspection") else None),
            numerics=(TrainingNumerics.from_dict(d["numerics"])
                      if d.get("numerics") else None),
        )


class GraphBuilder:
    """Fluent DAG builder (reference ``GraphBuilder`` :379,:498)."""

    def __init__(self, parent):
        self._parent = parent
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._nodes: List[GraphNode] = []
        self._input_types: Dict[str, InputType] = {}
        self._compute_dtype: Optional[str] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def compute_dtype(self, dtype: str) -> "GraphBuilder":
        """Mixed-precision compute policy: params/optimizer fp32, forward/
        backward math in ``dtype`` (same policy as ListBuilder.compute_dtype)."""
        if dtype not in ("float32", "bfloat16", "float16"):
            raise ValueError(f"unsupported compute_dtype '{dtype}'")
        self._compute_dtype = None if dtype == "float32" else dtype
        return self

    def backprop_type(self, kind: str, fwd_length: int = 20,
                      back_length: int = 20) -> "GraphBuilder":
        """``standard`` or ``truncated_bptt`` (reference GraphBuilder
        ``backpropType``/``tBPTTLength``)."""
        if kind not in ("standard", "truncated_bptt"):
            raise ValueError(f"unknown backprop type '{kind}'")
        self._backprop_type = kind
        self._tbptt_fwd = fwd_length
        self._tbptt_back = back_length
        return self

    def set_input_types(self, **types: InputType) -> "GraphBuilder":
        self._input_types.update(types)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        self._nodes.append(GraphNode(name, tuple(inputs), layer=layer.with_name(name)))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        self._nodes.append(GraphNode(name, tuple(inputs), vertex=vertex))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def build(self) -> GraphConfiguration:
        p = self._parent
        conf = GraphConfiguration(
            inputs=tuple(self._inputs),
            outputs=tuple(self._outputs),
            nodes=tuple(self._nodes),
            updater=p._updater,
            input_types={k: v.to_dict() for k, v in self._input_types.items()} or None,
            seed=p._seed,
            optimization_algo=p._optimization_algo,
            num_iterations=p._num_iterations,
            compute_dtype=self._compute_dtype,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            stability=p._stability,
            introspection=p._introspection,
            numerics=p._numerics,
        )
        conf.validate()
        # shape inference pass: complete layers with n_in from input types
        if self._input_types:
            conf = _infer_shapes(conf, self._input_types, p)
        else:
            conf = dataclasses.replace(
                conf,
                nodes=tuple(
                    dataclasses.replace(n, layer=p._apply_global_defaults(n.layer))
                    if n.layer is not None else n
                    for n in conf.nodes
                ),
            )
        conf.validate()
        for n in conf.nodes:
            if n.layer is not None:
                n.layer.validate()
        return conf


def _infer_shapes(conf: GraphConfiguration, input_types: Dict[str, InputType], parent) -> GraphConfiguration:
    types: Dict[str, InputType] = dict(input_types)
    by_name = {n.name: n for n in conf.nodes}
    new_nodes: Dict[str, GraphNode] = {}
    for name in conf.topological_order():
        node = by_name[name]
        in_types = [types[i] for i in node.inputs]
        if node.layer is not None:
            layer = parent._apply_global_defaults(node.layer)
            layer = layer.setup(in_types[0])
            types[name] = layer.output_type(in_types[0])
            new_nodes[name] = dataclasses.replace(node, layer=layer)
        else:
            types[name] = node.vertex.output_type(in_types)
            new_nodes[name] = node
    return dataclasses.replace(
        conf, nodes=tuple(new_nodes[n.name] for n in conf.nodes)
    )


class ComputationGraph(LazyScoreMixin):
    """DAG-network facade mirroring MultiLayerNetwork's API surface."""

    def __init__(self, conf: GraphConfiguration):
        self.conf = conf
        self.nodes = {n.name: n for n in conf.nodes}
        self.topo = conf.topological_order()
        self.params: Dict[str, Dict[str, jax.Array]] = {}
        self.net_state: Dict[str, Dict[str, jax.Array]] = {}
        self.updater_state: Dict[str, Any] = {}
        self.listeners: List[Any] = []
        self.iteration = 0
        self._score = None  # lazy score_value (LazyScoreMixin)
        self._keys = KeyStream(conf.seed)
        self._jit_cache: Dict[Any, Any] = {}
        self._stab_rt = None   # StabilityRuntime, created on first fit
        # output-layer nodes in declared output order
        self.output_nodes = [self.nodes[o] for o in conf.outputs]
        # streaming rnnTimeStep state: node name -> carry; _stream_pos is
        # the host-side mirror of the caches' device position scalar
        # (None = poisoned by unequal per-input chunk lengths -> the
        # capacity check syncs device positions instead)
        self._rnn_state: Dict[str, Any] = {}
        self._stream_pos: Optional[int] = 0

    @property
    def layers(self):
        return tuple(n.layer for n in self.conf.nodes if n.layer is not None)

    def init(self, dtype=jnp.float32) -> "ComputationGraph":
        params, net_state = {}, {}
        for n in self.conf.nodes:
            if n.layer is not None and n.layer.has_params():
                params[n.name] = n.layer.init(self._keys.next(), dtype)
            else:
                params[n.name] = {}
            if n.layer is not None:
                st = n.layer.init_state()
                if st:
                    net_state[n.name] = jax.tree_util.tree_map(lambda a: a.astype(dtype), st)
        self.params = params
        self.net_state = net_state
        from deeplearning4j_tpu.optimize import updaters as upd

        self.updater_state = upd.init_state(
            self.conf.updater, {k: v for k, v in params.items() if v}
        )
        if self.conf.stability is not None:
            from deeplearning4j_tpu.resilience import stability

            # guard/scale state rides in the updater-state pytree: it
            # stacks, shards, donates, and checkpoints like Adam moments
            self.updater_state[stability.STATE_KEY] = (
                stability.initial_state(self.conf.stability))
        if self.conf.introspection is not None:
            from deeplearning4j_tpu.observability import introspection

            # per-layer stat vectors ride in the updater-state pytree too
            introspection.ensure_state(self)
        if self.conf.numerics is not None:
            from deeplearning4j_tpu.observability import numerics

            # precision ledger: same reserved-subtree transport
            numerics.ensure_state(self)
        return self

    def num_params(self) -> int:
        # tree_leaves: composite layers nest their params arbitrarily deep
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    def params_to_vector(self) -> np.ndarray:
        leaves = jax.tree_util.tree_leaves(self.params)
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate([np.asarray(l).reshape(-1) for l in leaves])

    def set_params_vector(self, vec: np.ndarray) -> None:
        leaves, treedef = jax.tree_util.tree_flatten(self.params)
        total = sum(int(np.prod(l.shape)) for l in leaves)
        if total != vec.size:
            raise ValueError(f"param vector size {vec.size} != model size {total}")
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(jnp.asarray(vec[off : off + n], l.dtype).reshape(l.shape))
            off += n
        self.params = jax.tree_util.tree_unflatten(treedef, out)

    # ------------------------------------------------------------- forward
    def _forward(self, params, net_state, inputs: Dict[str, jax.Array], *,
                 train, rng, fmask=None, stop_at_preoutput=True,
                 carries=None):
        """Fold over topological order.  Output-layer nodes stop at
        preoutput (loss/activation applied by callers).  ``carries`` maps
        recurrent node name -> (h, c) initial state; the new carries are
        returned for TBPTT / rnnTimeStep (reference
        ``ComputationGraph.rnnActivateUsingStoredState`` :1719)."""
        acts: Dict[str, jax.Array] = dict(inputs)
        new_state = dict(net_state)
        cd = self.conf.compute_dtype
        if cd is not None:
            # mixed precision: cast float leaves into the compute dtype inside
            # the graph so grads flow back to fp32 params (MLN._forward policy)
            dt = jnp.dtype(cd)

            def _cast(a):
                return (a.astype(dt)
                        if hasattr(a, "dtype")
                        and jnp.issubdtype(a.dtype, jnp.floating) else a)

            params = jax.tree_util.tree_map(_cast, params)
            acts = {k: _cast(jnp.asarray(v)) for k, v in acts.items()}
        n_nodes = len(self.topo)
        rngs = jax.random.split(rng, n_nodes) if rng is not None else [None] * n_nodes
        out_names = set(self.conf.outputs)
        new_carries: Dict[str, Any] = {}
        for i, name in enumerate(self.topo):
            node = self.nodes[name]
            xs = [acts[inp] for inp in node.inputs]
            if node.layer is not None:
                layer = node.layer
                lstate = net_state.get(name, {})
                if isinstance(layer, OutputLayer) and name in out_names and stop_at_preoutput:
                    h = layer.maybe_dropout(xs[0], train=train, rng=rngs[i])
                    acts[name] = layer.pre_output(params[name], h)
                elif hasattr(layer, "apply_with_carry"):
                    carry = (carries or {}).get(name)
                    y, lst, new_carry = layer.apply_with_carry(
                        params[name], lstate, xs[0], carry,
                        train=train, rng=rngs[i], mask=fmask,
                    )
                    new_carries[name] = new_carry
                    acts[name] = y
                else:
                    from deeplearning4j_tpu.nn.layers.convolution import GlobalPoolingLayer

                    kw = {"mask": fmask} if isinstance(layer, GlobalPoolingLayer) else {}
                    y, lst = layer.apply(params[name], lstate, xs[0],
                                         train=train, rng=rngs[i], **kw)
                    if lst:
                        new_state[name] = lst
                    acts[name] = y
            else:
                if isinstance(node.vertex, LastTimeStepVertex):
                    acts[name] = node.vertex.apply(xs, mask=fmask)
                else:
                    acts[name] = node.vertex.apply(xs)
        return acts, new_state, new_carries

    def _loss_fn(self, params, net_state, inputs, labels, rng, fmask=None,
                 lmask=None, carries=None, train=True, collect_acts=False,
                 numerics_now=None):
        """inputs: dict name->array (or single array for 1-input graphs);
        labels: dict output-name->array or single array."""
        inputs = self._as_input_dict(inputs)
        labels = self._as_label_dict(labels)
        acts, new_state, new_carries = self._forward(
            params, net_state, inputs, train=train, rng=rng, fmask=fmask,
            carries=carries)
        total = jnp.zeros(())
        for node in self.output_nodes:
            layer = node.layer
            lm = lmask.get(node.name) if isinstance(lmask, dict) else lmask
            pre = acts[node.name]
            if self.conf.compute_dtype is not None:
                pre = pre.astype(jnp.float32)  # loss in full precision
            total = total + losses_mod.score(
                layer.loss, labels[node.name], pre, layer.activation, lm
            )
        for n in self.conf.nodes:
            if n.layer is not None and n.layer.has_params():
                total = total + n.layer.reg_score(params[n.name])
        if collect_acts:
            # introspection: per-layer-node activation summaries reduced
            # in-graph (same node order as IntrospectPlan.act_names)
            named = [(n.name, acts[n.name]) for n in self.conf.nodes
                     if n.layer is not None]
            policy = self.conf.introspection
            act_stats = {}
            if policy is not None:
                from deeplearning4j_tpu.observability import introspection

                act_stats = introspection.act_summary(
                    named, dead_eps=policy.dead_eps)
            npolicy = self.conf.numerics
            if npolicy is not None and npolicy.collect_activations:
                # precision ledger: activation dynamic-range blocks
                from deeplearning4j_tpu.observability import numerics

                act_stats.update(numerics.act_ranges(
                    named, policy=npolicy, now=numerics_now))
            return total, (new_state, new_carries, act_stats)
        return total, (new_state, new_carries)

    def _as_input_dict(self, inputs):
        if isinstance(inputs, dict):
            return inputs
        if len(self.conf.inputs) != 1:
            raise ValueError("Multi-input graph requires a dict of inputs")
        return {self.conf.inputs[0]: inputs}

    def _as_label_dict(self, labels):
        if isinstance(labels, dict):
            return labels
        if len(self.conf.outputs) != 1:
            raise ValueError("Multi-output graph requires a dict of labels")
        return {self.conf.outputs[0]: labels}

    # ---------------------------------------------------------- train step
    def _step_core(self):
        """The raw (un-jitted) SGD step shared by the per-batch train step
        and the scanned multi-step window (mirrors
        ``MultiLayerNetwork._step_core``)."""
        from deeplearning4j_tpu.observability import introspection, numerics
        from deeplearning4j_tpu.optimize import updaters as upd

        cfg = self.conf.updater
        lr_overrides = {
            n.name: n.layer.learning_rate
            for n in self.conf.nodes
            if n.layer is not None and n.layer.learning_rate is not None
        }

        policy = self.conf.stability
        plan = introspection.plan_for(self)
        nplan = numerics.plan_for(self)

        def step(params, upd_state, net_state, iteration, inputs, labels,
                 rng, fmask, lmask, carries):
            nstate = None
            if nplan is not None:
                nstate, upd_state = numerics.split_state(upd_state)
            if plan is not None:
                _, upd_state = introspection.split_state(upd_state)
            now = numerics.collect_now(nplan, iteration)
            kw = ({"collect_acts": True}
                  if numerics.wants_acts(plan, nplan) else {})
            if kw and now is not None:
                kw["numerics_now"] = now
            if policy is None:
                (loss, aux), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True
                )(params, net_state, inputs, labels, rng, fmask, lmask,
                  carries, **kw)
                new_ns, new_carries, act_stats = (
                    numerics.unpack_aux(plan, nplan, aux))
                grads = {k: v for k, v in grads.items() if v}
                updates, new_us = upd.update(cfg, grads, upd_state, iteration,
                                             lr_overrides, params=params)
                new_params = dict(params)
                for lname, u in updates.items():
                    new_params[lname] = upd.apply_updates(params[lname], u)
                introspection.attach(
                    new_us, plan, grads=grads, params=params,
                    new_params=new_params, iteration=iteration,
                    act_stats=act_stats)
                numerics.attach(
                    new_us, nplan, grads=grads, iteration=iteration,
                    act_stats=act_stats, prev=nstate, now=now)
                return new_params, new_us, new_ns, loss, new_carries
            # non-finite step guard + loss scaling: a poisoned step folds
            # into a device-side no-op (resilience/stability.py; same
            # structure as MultiLayerNetwork._step_core)
            from deeplearning4j_tpu.resilience import stability

            stab, inner = stability.split_state(upd_state)
            (_, (loss, aux)), grads = jax.value_and_grad(
                stability.scaled_loss(self._loss_fn, stab), has_aux=True
            )(params, net_state, inputs, labels, rng, fmask, lmask,
              carries, **kw)
            new_ns, new_carries, act_stats = (
                numerics.unpack_aux(plan, nplan, aux))
            new_params, new_us, new_ns, finite = (
                stability.apply_guarded_update(
                    policy, cfg, stab, inner, params, net_state,
                    loss, grads, new_ns, iteration, lr_overrides))
            introspection.attach(
                new_us, plan, grads=grads, params=params,
                new_params=new_params, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"])
            numerics.attach(
                new_us, nplan, grads=grads, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"],
                prev=nstate, now=now)
            if new_carries is not None and policy.skip_nonfinite:
                # poisoned TBPTT window: reset the recurrent stream state
                # rather than carrying NaN into the next window
                new_carries = stability.select(
                    finite, new_carries,
                    jax.tree_util.tree_map(jnp.zeros_like, new_carries))
            return new_params, new_us, new_ns, loss, new_carries

        return step

    def _get_train_step(self):
        if "train_step" not in self._jit_cache:
            self._jit_cache["train_step"] = instrument(
                jax.jit(self._step_core(), donate_argnums=(0, 1, 2)),
                "ComputationGraph.train_step",
                argnums=(3, 4, 5, 6, 7, 8, 9))
        return self._jit_cache["train_step"]

    def _make_scanned_step(self):
        """K weight updates in ONE dispatch — ``lax.scan`` over the step
        core, amortizing the ~1 ms host/tunnel dispatch floor to 1/K for
        small graphs (same design as
        ``MultiLayerNetwork._make_scanned_step``; PROFILE.md)."""
        core = self._step_core()

        def multi(params, upd_state, net_state, it0, xs, ys, rngs):
            def body(carry, inp):
                params, upd_state, net_state, it = carry
                x, y, rng = inp
                params, upd_state, net_state, loss, _ = core(
                    params, upd_state, net_state, it, x, y, rng,
                    None, None, None)
                return (params, upd_state, net_state, it + 1.0), loss

            (params, upd_state, net_state, _), losses = jax.lax.scan(
                body, (params, upd_state, net_state, it0), (xs, ys, rngs))
            return params, upd_state, net_state, losses

        return instrument(jax.jit(multi, donate_argnums=(0, 1, 2)),
                          "ComputationGraph.scanned_step",
                          argnums=(3, 4, 5, 6))

    def fit_scanned(self, batches, scan_steps: int, epochs: int = 1):
        """Amortized training: consecutive same-shape batches stacked
        ``scan_steps`` at a time into one scanned XLA program — same
        per-batch updates and RNG stream as ``fit`` over the same batches
        (the CG SGD path runs each batch once, so no num_iterations
        divergence is possible); listeners fire once per window with
        ``score_value`` the window's last loss; a short tail (or a shape
        change) runs the regular per-batch step.  SGD only; no masks or
        TBPTT."""
        if scan_steps < 1:
            raise ValueError(f"scan_steps={scan_steps} must be >= 1")
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            raise ValueError("fit_scanned requires SGD optimization")
        if self.conf.backprop_type == "truncated_bptt":
            raise ValueError("fit_scanned does not support TBPTT")
        if self.conf.introspection is not None:
            from deeplearning4j_tpu.observability import introspection

            introspection.ensure_state(self)
            self._introspect_live = None
        if self.conf.numerics is not None:
            from deeplearning4j_tpu.observability import numerics

            numerics.ensure_state(self)
            self._numerics_live = None
        scanned = self._jit_cache.setdefault(
            "scanned_step", self._make_scanned_step())
        for _ in range(epochs):
            window: list = []
            wshape = None
            for batch in batches:
                if hasattr(batch, "features_masks"):  # MultiDataSet
                    x, y, fm, lm = self._unpack_multi(batch)
                elif hasattr(batch, "features"):
                    x, y, fm, lm = (batch.features, batch.labels,
                                    batch.features_mask, batch.labels_mask)
                else:
                    x, y = batch[0], batch[1]
                    fm = batch[2] if len(batch) > 2 else None
                    lm = batch[3] if len(batch) > 3 else None
                if fm is not None or lm is not None:
                    raise ValueError("fit_scanned does not support masks")
                x = {k: np.asarray(v)
                     for k, v in self._as_input_dict(x).items()}
                y = {k: np.asarray(v)
                     for k, v in self._as_label_dict(y).items()}
                shape = ({k: v.shape for k, v in x.items()},
                         {k: v.shape for k, v in y.items()})
                if window and shape != wshape:
                    self._flush_window(window, scanned, scan_steps)
                    window = []
                wshape = shape
                window.append((x, y))
                if len(window) == scan_steps:
                    self._flush_window(window, scanned, scan_steps)
                    window = []
            if window:
                self._flush_window(window, scanned, scan_steps)
        return self

    def _flush_window(self, window, scanned, scan_steps):
        if len(window) == scan_steps:
            tel = fit_telemetry("ComputationGraph")
            batch = len(next(iter(window[0][0].values())))
            t0 = time.perf_counter()
            with step_guard("fit_window", model="ComputationGraph",
                            iteration=self.iteration, steps=len(window)):
                with tel.span(self.iteration):
                    xs = {k: jnp.asarray(np.stack([b[0][k] for b in window]))
                          for k in window[0][0]}
                    ys = {k: jnp.asarray(np.stack([b[1][k] for b in window]))
                          for k in window[0][1]}
                    rngs = jnp.stack([self._keys.next() for _ in window])
                    it0 = jnp.asarray(self.iteration, jnp.float32)
                    (self.params, self.updater_state, self.net_state,
                     losses) = scanned(self.params, self.updater_state,
                                       self.net_state, it0, xs, ys, rngs)
            self.score_value = losses[-1]
            self.iteration += len(window)
            tel.record_step(time.perf_counter() - t0, batch, losses[-1],
                            steps=len(window), model=self)
            # listeners fire once per window, so they get the WINDOW's
            # sample count — samples/sec = samples / (window wall time)
            notify_listeners(self, batch * len(window))
        else:  # short tail: regular per-batch step keeps semantics exact
            for x, y in window:
                self._one_step(x, y, None, None, carries=None)

    def fit(self, data, labels=None, *, fmask=None, lmask=None,
            checkpoint_manager=None, retry_policy=None):
        """fit(inputs, labels) or fit(iterable of DataSet / MultiDataSet /
        tuples).  MultiDataSet features/labels map positionally onto
        ``conf.inputs`` / ``conf.outputs`` (reference
        ``ComputationGraph.fit(MultiDataSetIterator)`` :599-747).

        ``checkpoint_manager=`` / ``retry_policy=`` wire the resilience
        layer exactly as in ``MultiLayerNetwork.fit``: auto-resume with
        batch skipping, boundary saves, clean preemption stop, transient
        step retry (docs/resilience.md)."""
        from deeplearning4j_tpu.observability import profiling, shardstats

        prof = profiling.active_profiler()
        if prof is not None:
            # memory attribution: flight/watchdog dumps show this model's
            # per-leaf param/updater byte breakdown (weakly held)
            prof.track_model(self, "ComputationGraph")
        # sharding ledger (per-tree bytes/replication; metadata walk only,
        # once per fit call) — flight dumps and GET /memory read it
        shardstats.record_model_ledger(self, "ComputationGraph")
        res = None
        if checkpoint_manager is not None or retry_policy is not None:
            from deeplearning4j_tpu.resilience import FitResilience

            res = FitResilience("ComputationGraph", checkpoint_manager,
                                retry_policy, net=self)
        if self.conf.stability is not None:
            from deeplearning4j_tpu.resilience import stability

            stability.ensure_state(self)
            created = self._stab_rt is None
            if created:
                self._stab_rt = stability.StabilityRuntime(
                    "ComputationGraph", self.conf.stability)
            if created or (res is not None and res.resumed_from is not None):
                # a restored nonfinite_total is history, not fresh evidence
                self._stab_rt.baseline_from(
                    self.updater_state.get(stability.STATE_KEY))
        if self.conf.introspection is not None:
            from deeplearning4j_tpu.observability import introspection

            introspection.ensure_state(self)
            # facade updater_state is authoritative during a solo fit
            self._introspect_live = None
        if self.conf.numerics is not None:
            from deeplearning4j_tpu.observability import numerics

            numerics.ensure_state(self)
            self._numerics_live = None
        from deeplearning4j_tpu.resilience import preemption_requested

        try:
            if labels is not None:
                # the single-pair path is one "batch": same skip /
                # preemption / boundary-save duties as the iterable loop
                # (user-driven loops call fit(x, y) repeatedly)
                if res is not None and res.skip_window(self._batch_adv(data)):
                    return self
                if preemption_requested():
                    if res is not None:
                        res.on_preempt(self)
                    return self
                self._fit_one(data, labels, fmask, lmask, res)
                if res is not None:
                    res.after_step(self)
                if self._stab_rt is not None:
                    self._stab_rt.poll_net(self, res)
                return self
            for batch in data:
                if hasattr(batch, "features_masks"):  # MultiDataSet
                    x, y, fm, lm = self._unpack_multi(batch)
                elif hasattr(batch, "features"):
                    x, y, fm, lm = (batch.features, batch.labels,
                                    batch.features_mask, batch.labels_mask)
                else:
                    x, y = batch[0], batch[1]
                    fm = batch[2] if len(batch) > 2 else None
                    lm = batch[3] if len(batch) > 3 else None
                if res is not None and res.skip_window(self._batch_adv(x)):
                    continue   # auto-resume: batch covered by the ckpt
                if preemption_requested():
                    if res is not None:
                        res.on_preempt(self)
                    break   # preemption: stop cleanly at a boundary
                self._fit_one(x, y, fm, lm, res)
                if res is not None:
                    res.after_step(self)
                if self._stab_rt is not None:
                    # sentinel boundary: no-op except every check_every-th
                    # batch (harvest + possible backoff/rewind escalation)
                    self._stab_rt.poll_net(self, res)
        except Exception as e:
            # fit-loop exception: leave the same flight-recorder report a
            # hang would (events + live spans + registry snapshot)
            crash_dump("fit_exception", model="ComputationGraph",
                       iteration=self.iteration, error=repr(e))
            raise
        finally:
            if self._stab_rt is not None:
                # final harvest: the tail past the last check boundary
                # still lands in the non-finite counter
                self._stab_rt.flush(self)
        return self

    def _unpack_multi(self, mds):
        """Positional MultiDataSet -> named input/label dicts."""
        if len(mds.features) != len(self.conf.inputs):
            raise ValueError(
                f"MultiDataSet has {len(mds.features)} feature arrays, graph "
                f"declares {len(self.conf.inputs)} inputs")
        if len(mds.labels) != len(self.conf.outputs):
            raise ValueError(
                f"MultiDataSet has {len(mds.labels)} label arrays, graph "
                f"declares {len(self.conf.outputs)} outputs")
        x = dict(zip(self.conf.inputs, mds.features))
        y = dict(zip(self.conf.outputs, mds.labels))
        fm = None
        if mds.features_masks is not None:
            present = [m for m in mds.features_masks if m is not None]
            if len(present) > 1:
                raise ValueError("at most one features mask is supported")
            fm = present[0] if present else None
        lm = None
        if mds.labels_masks is not None:
            lm = {name: m for name, m in zip(self.conf.outputs, mds.labels_masks)
                  if m is not None} or None
        return x, y, fm, lm

    def _batch_adv(self, x) -> int:
        """How many ITERATIONS one batch advances — the resume-skip unit.
        1 everywhere except SGD TBPTT, where one batch runs one iteration
        per fwd-length window (the solver path also advances by exactly 1,
        after the solve)."""
        if (self.conf.optimization_algo == "stochastic_gradient_descent"
                and self.conf.backprop_type == "truncated_bptt"):
            temporal = [np.shape(a)[1]
                        for a in self._as_input_dict(x).values()
                        if np.ndim(a) >= 3]
            if temporal:
                return -(-max(temporal) // self.conf.tbptt_fwd_length)
        return 1

    def _fit_one(self, x, y, fm, lm, res=None):
        """One batch; the resilience retry scope is per ITERATION — the
        single SGD step, each TBPTT window, or the whole solver solve
        (which only writes params/iteration after it finishes)."""
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            if res is not None:
                return res.step(lambda: self._fit_solver(x, y, fm, lm),
                                self.iteration, net=self)
            return self._fit_solver(x, y, fm, lm)
        if self.conf.backprop_type == "truncated_bptt":
            return self._fit_tbptt(x, y, fm, lm, res)
        if res is not None:
            res.step(lambda: self._one_step(x, y, fm, lm, carries=None),
                     self.iteration, net=self)
        else:
            self._one_step(x, y, fm, lm, carries=None)

    def _one_step(self, x, y, fm, lm, carries):
        from deeplearning4j_tpu.resilience import get_fault_injector

        inj = get_fault_injector()
        if inj is not None and inj.has_poison():
            # deterministic chaos: single-device fit loops poison under
            # worker id "0" (docs/resilience.md "Stability")
            x, y = inj.poison_batch("0", self.iteration, x, y)
        step = self._get_train_step()
        x = jax.tree_util.tree_map(jnp.asarray, self._as_input_dict(x))
        y = jax.tree_util.tree_map(jnp.asarray, self._as_label_dict(y))
        batch = int(next(iter(x.values())).shape[0]) if x else None
        tel = fit_telemetry("ComputationGraph")
        t0 = time.perf_counter()
        with step_guard("fit_step", model="ComputationGraph",
                        iteration=self.iteration):
            with tel.span(self.iteration):
                (self.params, self.updater_state, self.net_state, loss,
                 new_carries) = step(
                    self.params, self.updater_state, self.net_state,
                    jnp.asarray(float(self.iteration)), x, y,
                    self._keys.next(),
                    None if fm is None else jax.tree_util.tree_map(
                        jnp.asarray, fm),
                    None if lm is None else jax.tree_util.tree_map(
                        jnp.asarray, lm),
                    carries,
                )
        self.score_value = loss  # device scalar; fetched lazily on read
        self.iteration += 1
        tel.record_step(time.perf_counter() - t0, batch, loss, model=self)
        notify_listeners(self, batch)
        return new_carries

    def _fit_tbptt(self, x, y, fm, lm, res=None):
        """Truncated BPTT over the DAG: slice the time axis of every input/
        label/mask into fwd-length windows, carrying recurrent-node state
        (detached) across windows (reference ``ComputationGraph``
        ``doTruncatedBPTT`` :1549).  Retry scope is per WINDOW — each
        window is one committed iteration."""
        x = self._as_input_dict(x)
        y = self._as_label_dict(y)
        temporal = [a.shape[1] for a in x.values() if np.ndim(a) >= 3]
        if not temporal:
            raise ValueError(
                "TBPTT requires at least one rank-3 [batch, time, features] "
                "input; use backprop_type='standard' for feed-forward graphs")
        T = max(temporal)
        L = self.conf.tbptt_fwd_length
        carries = None
        for t0 in range(0, T, L):
            sl = slice(t0, min(t0 + L, T))

            def one_window(c=carries, sl=sl):
                return self._one_step(
                    self._tbptt_slice_data(x, sl),
                    self._tbptt_slice_data(y, sl),
                    self._tbptt_slice_mask(fm, sl),
                    self._tbptt_slice_mask(lm, sl),
                    c,
                )

            if res is not None:
                carries = res.step(one_window, self.iteration, net=self)
            else:
                carries = one_window()
            carries = jax.lax.stop_gradient(carries)

    @staticmethod
    def _tbptt_slice_data(tree, sl):
        """Time-slice rank-3 sequences; rank-2 arrays are static
        feed-forward features / one-hot labels, passed whole."""
        if tree is None:
            return None
        return jax.tree_util.tree_map(
            lambda a: a[:, sl] if np.ndim(a) >= 3 else a, tree)

    @staticmethod
    def _tbptt_slice_mask(tree, sl):
        """Masks are [batch, time] — rank-2 IS temporal here."""
        if tree is None:
            return None
        return jax.tree_util.tree_map(
            lambda a: a[:, sl] if np.ndim(a) >= 2 else a, tree)

    def _fit_solver(self, x, y, fm, lm):
        """Full-batch solver path (CG/LBFGS/line-search GD); see
        ``MultiLayerNetwork._fit_solver``. Reference ``Solver.java:47-74``."""
        from deeplearning4j_tpu.optimize import solvers as solvers_mod

        args = (
            self.net_state,
            jax.tree_util.tree_map(jnp.asarray, self._as_input_dict(x)),
            jax.tree_util.tree_map(jnp.asarray, self._as_label_dict(y)),
            self._keys.next(),
            None if fm is None else jnp.asarray(fm),
            None if lm is None else jnp.asarray(lm),
        )

        def loss_fn(params, net_state, x, y, rng, fm, lm):
            return self._loss_fn(params, net_state, x, y, rng, fm, lm)

        solvers_mod.fit_model_with_solver(
            self, loss_fn, args, self.conf.optimization_algo,
            self.conf.num_iterations,
        )

    # ------------------------------------------------------------ inference
    def output(self, inputs, fmask=None):
        if "output" not in self._jit_cache:

            def out(params, net_state, inputs, fmask):
                from deeplearning4j_tpu.nn import activations

                acts, _, _ = self._forward(params, net_state, inputs,
                                           train=False, rng=None, fmask=fmask)
                outs = []
                for node in self.output_nodes:
                    pre = acts[node.name]
                    if self.conf.compute_dtype is not None:
                        pre = pre.astype(jnp.float32)  # fp32 API boundary
                    outs.append(activations.get(node.layer.activation)(pre))
                return outs

            self._jit_cache["output"] = jax.jit(out)
        inputs = jax.tree_util.tree_map(jnp.asarray, self._as_input_dict(inputs))
        outs = self._jit_cache["output"](
            self.params, self.net_state, inputs,
            None if fmask is None else jnp.asarray(fmask),
        )
        return outs[0] if len(outs) == 1 else outs

    def evaluate(self, iterator, evaluation=None):
        """Classification metrics over a DataSet/MultiDataSet iterator
        (reference ``ComputationGraph.doEvaluation`` — single-output graphs)."""
        from deeplearning4j_tpu.evaluation import Evaluation

        if len(self.conf.outputs) != 1:
            raise ValueError("evaluate() supports single-output graphs; use "
                             "output() + per-head Evaluation for multi-output")
        ev = evaluation or Evaluation()
        for batch in iterator:
            if hasattr(batch, "features_masks"):  # MultiDataSet
                x, y, fm, lm = self._unpack_multi(batch)
                lm = None if lm is None else next(iter(lm.values()))
                y = y[self.conf.outputs[0]]
            else:
                x, y = batch.features, batch.labels
                fm, lm = batch.features_mask, batch.labels_mask
            ev.eval(y, self.output(x, fmask=fm), mask=lm)
        return ev

    def feed_forward(self, inputs, train: bool = False, fmask=None):
        """All vertex activations as a name->array dict (reference
        ``ComputationGraph.feedForward()`` :1012-1036; output vertices carry
        their post-activation values)."""
        from deeplearning4j_tpu.nn import activations

        inputs = jax.tree_util.tree_map(jnp.asarray, self._as_input_dict(inputs))
        rng = self._keys.next() if train else None
        acts, _, _ = self._forward(self.params, self.net_state, inputs,
                                   train=train, rng=rng, fmask=fmask)
        out = {}
        out_names = set(self.conf.outputs)
        for name, a in acts.items():
            if name in out_names:
                a = activations.get(self.nodes[name].layer.activation)(
                    a.astype(jnp.float32) if self.conf.compute_dtype else a)
            elif self.conf.compute_dtype is not None and hasattr(a, "dtype") \
                    and jnp.issubdtype(a.dtype, jnp.floating):
                a = a.astype(jnp.float32)  # fp32 API boundary
            out[name] = a
        return out

    def score(self, inputs=None, labels=None, dataset=None, fmask=None,
              lmask=None) -> float:
        if dataset is not None:
            if hasattr(dataset, "features"):
                inputs, labels = dataset.features, dataset.labels
                fmask = fmask if fmask is not None else getattr(dataset, "features_mask", None)
                lmask = lmask if lmask is not None else getattr(dataset, "labels_mask", None)
            else:
                inputs, labels = dataset[0], dataset[1]
        inputs = jax.tree_util.tree_map(jnp.asarray, self._as_input_dict(inputs))
        labels = jax.tree_util.tree_map(jnp.asarray, self._as_label_dict(labels))
        loss, _ = self._loss_fn(self.params, self.net_state, inputs, labels,
                                None, fmask=fmask, lmask=lmask, train=False)
        return float(loss)

    # ------------------------------------------------- streaming rnnTimeStep
    def rnn_clear_previous_state(self):
        """Reference ``ComputationGraph.rnnClearPreviousState`` :1686."""
        self._rnn_state = {}
        self._stream_pos = 0

    def _id_consumer(self, input_name: str):
        """The EmbeddingLayer consuming this graph input, if any — its
        inputs are integer token ids, not feature vectors.  The map is
        static for the life of the graph; memoized because this sits in
        the per-token streaming loop."""
        cache = getattr(self, "_id_consumer_map", None)
        if cache is None:
            from deeplearning4j_tpu.nn.layers.dense import EmbeddingLayer

            cache = {}
            for node in self.nodes.values():
                if node.layer is not None and isinstance(node.layer,
                                                         EmbeddingLayer):
                    for inp in node.inputs:
                        cache[inp] = node.layer
            self._id_consumer_map = cache
        return cache.get(input_name)

    def rnn_time_step(self, inputs, fmask=None):
        """Stateful streaming inference (reference
        ``ComputationGraph.rnnTimeStep`` :1674): feed one (or a few)
        timesteps; recurrent-node carries persist across calls."""
        from deeplearning4j_tpu.models.common import (
            check_cache_capacity, seed_stream_caches,
        )

        inputs = self._as_input_dict(inputs)
        inputs = {k: jnp.asarray(v) for k, v in inputs.items()}
        # per-input expansion: id inputs (feeding an EmbeddingLayer) follow
        # the MLN id rules; feature inputs treat rank-2 as one timestep
        squeeze = False
        expanded = {}
        for name, v in inputs.items():
            emb = self._id_consumer(name)
            if emb is not None:
                sq = v.ndim == 1 or (
                    emb.collapse_column and v.ndim == 2 and v.shape[1] == 1)
                if v.ndim == 1:
                    v = v[:, None]
                if v.ndim == 2 and emb.collapse_column:
                    v = v[..., None]
            else:
                sq = v.ndim == 2
                if sq:
                    v = v[:, None, :]
            squeeze = squeeze or sq
            expanded[name] = v
        inputs = expanded
        first = next(iter(inputs.values()))
        if not self._rnn_state:
            self._stream_pos = 0
        carries = seed_stream_caches(
            ((n, self.nodes[n].layer) for n in self.topo
             if self.nodes[n].layer is not None),
            self._rnn_state, first.shape[0], self.conf.compute_dtype)
        # the longest time axis across inputs bounds what any attention
        # cache may be asked to append this call
        t_all = {int(v.shape[1]) for v in inputs.values() if v.ndim >= 2}
        t_new = max(t_all, default=1)
        # host-side position counter: no device->host sync per streamed
        # chunk.  Valid only while every input streams the same number of
        # timesteps per call (caches fed by a shorter input would advance
        # less than the counter) — unequal chunks poison the counter and
        # the check falls back to syncing each cache's device position.
        if len(t_all) > 1:
            self._stream_pos = None
        pos = self._stream_pos if isinstance(self._stream_pos, int) else None
        check_cache_capacity(carries, t_new, pos=pos)
        carries = carries or None
        acts, _, new_carries = self._forward(
            self.params, self.net_state, inputs, train=False, rng=None,
            fmask=fmask, carries=carries,
        )
        self._rnn_state = new_carries
        if isinstance(self._stream_pos, int):
            self._stream_pos += t_new
        from deeplearning4j_tpu.nn import activations

        outs = []
        for node in self.output_nodes:
            pre = acts[node.name]
            if self.conf.compute_dtype is not None:
                pre = pre.astype(jnp.float32)
            o = activations.get(node.layer.activation)(pre)
            outs.append(o[:, -1] if squeeze and o.ndim == 3 else o)
        return outs[0] if len(outs) == 1 else outs

    # -------------------------------------------------------------- pretrain
    def pretrain(self, batches, epochs: int = 1):
        """Layerwise unsupervised pretraining of AutoEncoder/RBM layer
        vertices, in topological order (reference ``ComputationGraph.pretrain``
        :478: trains each pretrainable vertex on the DAG activations feeding
        it)."""
        from deeplearning4j_tpu.nn.layers.autoencoder import AutoEncoder, RBM

        batches = list(batches) if not isinstance(batches, list) else batches
        for name in self.topo:
            node = self.nodes[name]
            if node.layer is None or not isinstance(node.layer, (AutoEncoder, RBM)):
                continue
            layer = node.layer

            def ploss(lparams, x, rng, _layer=layer):
                return _layer.pretrain_loss(lparams, x, rng)

            grad_fn = jax.jit(jax.value_and_grad(ploss))
            lr = layer.learning_rate or self.conf.updater.learning_rate
            for _ in range(epochs):
                for batch in batches:
                    if hasattr(batch, "features_masks"):
                        x, _, _, _ = self._unpack_multi(batch)
                    elif hasattr(batch, "features"):
                        x = batch.features
                    else:
                        x = batch[0] if isinstance(batch, (tuple, list)) else batch
                    x = jax.tree_util.tree_map(jnp.asarray, self._as_input_dict(x))
                    # DAG activations feeding this node (test mode, current params)
                    acts, _, _ = self._forward(self.params, self.net_state, x,
                                               train=False, rng=None,
                                               stop_at_preoutput=True)
                    h = acts[node.inputs[0]]  # _forward seeds acts with inputs
                    loss, g = grad_fn(self.params[name], h, self._keys.next())
                    self.params[name] = jax.tree_util.tree_map(
                        lambda p, gg: p - lr * gg, self.params[name], g
                    )
        return self

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def clone(self) -> "ComputationGraph":
        net = ComputationGraph(self.conf)
        net.params = jax.tree_util.tree_map(lambda a: a, self.params)
        net.net_state = jax.tree_util.tree_map(lambda a: a, self.net_state)
        net.updater_state = jax.tree_util.tree_map(lambda a: a, self.updater_state)
        net.iteration = self.iteration
        return net

    def save(self, path, save_updater: bool = True):
        from deeplearning4j_tpu.models import serialization

        serialization.write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path) -> "ComputationGraph":
        from deeplearning4j_tpu.models import serialization

        return serialization.restore_computation_graph(path)
