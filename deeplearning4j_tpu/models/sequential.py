"""MultiLayerNetwork — the sequential-network facade.

Reference: ``nn/multilayer/MultiLayerNetwork.java`` (init :348, fit :1029,
feedForward :619-711, backprop :1085, TBPTT :1176, output :1525-1607,
rnnTimeStep :2195).  Functional redesign: params/state live in pytrees on
this facade; the training step is ONE jitted pure function
(loss -> jax.grad -> updater -> param update), replacing the reference's
Solver/StochasticGradientDescent object dance (``optimize/solvers/
StochasticGradientDescent.java:51-73``) with an XLA program.  The
reference's flattened-params invariant (single param vector,
``MultiLayerNetwork.java:97-98``) survives as ``params_to_vector`` /
``set_params_vector`` — used by serialization, param averaging, and tests.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.backend.rng import KeyStream
from deeplearning4j_tpu.models.common import LazyScoreMixin, notify_listeners
from deeplearning4j_tpu.observability import (
    crash_dump, fit_telemetry, instrument, step_guard,
)
from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.dense import OutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.optimize import updaters as upd


def _is_recurrent(layer) -> bool:
    return hasattr(layer, "apply_with_carry")


class MultiLayerNetwork(LazyScoreMixin):
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: Tuple[Layer, ...] = conf.layers
        self.params: Dict[str, Dict[str, jax.Array]] = {}
        self.net_state: Dict[str, Dict[str, jax.Array]] = {}
        self.updater_state: Dict[str, Any] = {}
        self.listeners: List[Any] = []
        self.iteration = 0
        self._score = None  # lazy score_value (LazyScoreMixin)
        self._keys = KeyStream(conf.seed)
        self._jit_cache: Dict[Any, Any] = {}
        self._stab_rt = None   # StabilityRuntime, created on first fit
        # streaming rnnTimeStep state: layer_name -> carry; _stream_pos is
        # the host-side mirror of the caches' device position scalar
        self._rnn_state: Dict[str, Any] = {}
        self._stream_pos: int = 0

    # ------------------------------------------------------------------ init
    def init(self, dtype=jnp.float32) -> "MultiLayerNetwork":
        params, net_state = {}, {}
        for layer in self.layers:
            if layer.has_params():
                params[layer.name] = layer.init(self._keys.next(), dtype)
            else:
                params[layer.name] = {}
            st = layer.init_state()
            if st:
                net_state[layer.name] = jax.tree_util.tree_map(
                    lambda a: a.astype(dtype), st
                )
        self.params = params
        self.net_state = net_state
        self.updater_state = upd.init_state(self.conf.updater, self._trainable(params))
        if self.conf.stability is not None:
            from deeplearning4j_tpu.resilience import stability

            # guard/scale state rides in the updater-state pytree: it
            # stacks, shards, donates, and checkpoints like Adam moments
            self.updater_state[stability.STATE_KEY] = (
                stability.initial_state(self.conf.stability))
        if self.conf.introspection is not None:
            from deeplearning4j_tpu.observability import introspection

            # per-layer stat vectors ride in the updater-state pytree
            # too: stacked per replica, replicated by the sync master,
            # donated, checkpointed (docs/observability.md)
            introspection.ensure_state(self)
        if self.conf.numerics is not None:
            from deeplearning4j_tpu.observability import numerics

            # precision ledger: same reserved-subtree transport
            numerics.ensure_state(self)
        return self

    def _trainable(self, params):
        return {k: v for k, v in params.items() if v}

    def num_params(self) -> int:
        # tree_leaves: composite layers (ResidualBlock) nest their params
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    # ----------------------------------------------------- flattened params
    def params_to_vector(self) -> np.ndarray:
        """Single flat param vector (reference flattenedParams invariant)."""
        leaves = jax.tree_util.tree_leaves(self.params)
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate([np.asarray(l).reshape(-1) for l in leaves])

    def set_params_vector(self, vec: np.ndarray) -> None:
        leaves, treedef = jax.tree_util.tree_flatten(self.params)
        total = sum(int(np.prod(l.shape)) for l in leaves)
        if total != vec.size:
            raise ValueError(f"param vector size {vec.size} != model size {total}")
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(jnp.asarray(vec[off : off + n], l.dtype).reshape(l.shape))
            off += n
        self.params = jax.tree_util.tree_unflatten(treedef, out)

    # --------------------------------------------------------------- forward
    def _forward(self, params, net_state, x, *, train, rng, fmask=None,
                 carries=None, collect=False):
        """Pure forward through preprocessors + layers.

        Returns (last_pre_activation_input, activations list if collect,
        new_net_state, new_carries).  The output layer is applied EXCEPT its
        loss head; callers use layer.pre_output for scoring/inference.
        """
        acts = []
        new_state = dict(net_state)
        new_carries = {}
        h = x
        cd = self.conf.compute_dtype
        if cd is not None:
            # mixed precision: cast float leaves to the compute dtype; the
            # cast sits inside the graph, so grads flow back to fp32 params
            # (loss and updater math stay fp32)
            dt = jnp.dtype(cd)

            def _cast(a):
                return (a.astype(dt)
                        if hasattr(a, "dtype")
                        and jnp.issubdtype(a.dtype, jnp.floating) else a)

            params = jax.tree_util.tree_map(_cast, params)
            h = _cast(jnp.asarray(h))
        n = len(self.layers)
        rngs = jax.random.split(rng, n) if rng is not None else [None] * n
        for i, layer in enumerate(self.layers):
            if i in self.conf.preprocessors:
                h = self.conf.preprocessors[i](h)
            lstate = net_state.get(layer.name, {})
            if _is_recurrent(layer):
                carry = (carries or {}).get(layer.name)
                h, lst, new_carry = layer.apply_with_carry(
                    params[layer.name], lstate, h, carry,
                    train=train, rng=rngs[i], mask=fmask,
                )
                new_carries[layer.name] = new_carry
            elif isinstance(layer, (OutputLayer,)):
                # output head: stop at preoutput; activation applied on demand
                h = self.maybe_flatten_time(layer, h)
                h = layer.maybe_dropout(h, train=train, rng=rngs[i])
                h = layer.pre_output(params[layer.name], h)
            else:
                from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
                from deeplearning4j_tpu.nn.layers.composite import ResidualBlock
                from deeplearning4j_tpu.nn.layers.convolution import GlobalPoolingLayer

                mask_aware = (GlobalPoolingLayer, SelfAttentionLayer, ResidualBlock)
                kw = {"mask": fmask} if isinstance(layer, mask_aware) else {}
                h, lst = layer.apply(params[layer.name], lstate, h,
                                     train=train, rng=rngs[i], **kw)
                if lst:
                    new_state[layer.name] = lst
            if collect:
                acts.append(h)
        return h, acts, new_state, new_carries

    @staticmethod
    def maybe_flatten_time(layer, h):
        return h

    # ----------------------------------------------------------------- score
    def _loss_fn(self, params, net_state, x, y, rng, fmask=None, lmask=None,
                 carries=None, train=True, collect_acts=False,
                 numerics_now=None):
        out_layer = self.layers[-1]
        if not isinstance(out_layer, OutputLayer):
            raise ValueError("Last layer must be an OutputLayer/RnnOutputLayer for fit()")
        pre, acts, new_state, new_carries = self._forward(
            params, net_state, x, train=train, rng=rng, fmask=fmask,
            carries=carries, collect=collect_acts
        )
        if self.conf.compute_dtype is not None:
            pre = pre.astype(jnp.float32)  # loss in full precision
        data_loss = losses_mod.score(out_layer.loss, y, pre, out_layer.activation, lmask)
        reg = jnp.zeros(())
        for layer in self.layers:
            if layer.has_params():
                reg = reg + layer.reg_score(params[layer.name])
        if collect_acts:
            # introspection: summarize every layer's activations while
            # they are still live in the graph (reduced to [A] scalars
            # immediately — the full activations are never carried out)
            named = list(zip((l.name for l in self.layers), acts))
            policy = self.conf.introspection
            act_stats = {}
            if policy is not None:
                from deeplearning4j_tpu.observability import introspection

                act_stats = introspection.act_summary(
                    named, dead_eps=policy.dead_eps)
            npolicy = self.conf.numerics
            if npolicy is not None and npolicy.collect_activations:
                # precision ledger: activation dynamic-range blocks,
                # reduced in-graph the same way
                from deeplearning4j_tpu.observability import numerics

                act_stats.update(numerics.act_ranges(
                    named, policy=npolicy, now=numerics_now))
            return data_loss + reg, (new_state, new_carries, act_stats)
        return data_loss + reg, (new_state, new_carries)

    # ------------------------------------------------------------ train step
    def _step_core(self):
        """The raw (un-jitted) SGD step shared by the per-batch train step
        and the scanned multi-step window.  With ``conf.stability`` set,
        the step is wrapped by the non-finite guard: the loss is scaled
        before ``grad`` (mixed-precision loss scaling), gradients are
        unscaled and checked all-finite, and a poisoned step folds into a
        device-side no-op (``params = where(finite, new, old)``; updater
        and net state likewise) — zero host syncs, zero recompiles
        (resilience/stability.py).  ``stability=None`` keeps the exact
        pre-guard trace."""
        from deeplearning4j_tpu.observability import introspection, numerics

        updater_cfg = self.conf.updater
        policy = self.conf.stability
        plan = introspection.plan_for(self)
        nplan = numerics.plan_for(self)
        lr_overrides = {
            l.name: l.learning_rate for l in self.layers if l.learning_rate is not None
        }

        def step(params, upd_state, net_state, iteration, x, y, rng, fmask, lmask, carries):
            nstate = None
            if nplan is not None:
                nstate, upd_state = numerics.split_state(upd_state)
            if plan is not None:
                _, upd_state = introspection.split_state(upd_state)
            now = numerics.collect_now(nplan, iteration)
            kw = ({"collect_acts": True}
                  if numerics.wants_acts(plan, nplan) else {})
            if kw and now is not None:
                kw["numerics_now"] = now
            if policy is None:
                (loss, aux), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True
                )(params, net_state, x, y, rng, fmask, lmask, carries, **kw)
                new_net_state, new_carries, act_stats = (
                    numerics.unpack_aux(plan, nplan, aux))
                grads = {k: v for k, v in grads.items() if v}
                updates, new_upd_state = upd.update(
                    updater_cfg, grads, upd_state, iteration, lr_overrides,
                    params=params,
                )
                new_params = dict(params)
                for lname, u in updates.items():
                    new_params[lname] = upd.apply_updates(params[lname], u)
                introspection.attach(
                    new_upd_state, plan, grads=grads, params=params,
                    new_params=new_params, iteration=iteration,
                    act_stats=act_stats)
                numerics.attach(
                    new_upd_state, nplan, grads=grads, iteration=iteration,
                    act_stats=act_stats, prev=nstate, now=now)
                return new_params, new_upd_state, new_net_state, loss, new_carries
            from deeplearning4j_tpu.resilience import stability

            stab, inner = stability.split_state(upd_state)
            (_, (loss, aux)), grads = (
                jax.value_and_grad(
                    stability.scaled_loss(self._loss_fn, stab), has_aux=True
                )(params, net_state, x, y, rng, fmask, lmask, carries, **kw))
            new_net_state, new_carries, act_stats = (
                numerics.unpack_aux(plan, nplan, aux))
            new_params, new_upd_state, new_net_state, finite = (
                stability.apply_guarded_update(
                    policy, updater_cfg, stab, inner, params, net_state,
                    loss, grads, new_net_state, iteration, lr_overrides))
            # grads here are loss-scaled; norms unscale exactly
            introspection.attach(
                new_upd_state, plan, grads=grads, params=params,
                new_params=new_params, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"])
            numerics.attach(
                new_upd_state, nplan, grads=grads, iteration=iteration,
                act_stats=act_stats, grad_scale=1.0 / stab["loss_scale"],
                prev=nstate, now=now)
            if new_carries is not None and policy.skip_nonfinite:
                # a poisoned TBPTT window must not smuggle NaN hidden
                # state into the next window: reset the stream instead
                new_carries = stability.select(
                    finite, new_carries,
                    jax.tree_util.tree_map(jnp.zeros_like, new_carries))
            return new_params, new_upd_state, new_net_state, loss, new_carries

        return step

    def _make_train_step(self, with_carry: bool):
        return instrument(jax.jit(self._step_core(), donate_argnums=(0, 1, 2)),
                          "MultiLayerNetwork.train_step",
                          argnums=(3, 4, 5, 6, 7, 8, 9))

    def _make_scanned_step(self):
        """K weight updates in ONE dispatch: ``lax.scan`` over the step
        core.  Small models (LeNet-class) are dispatch-bound — ~1 ms
        host/tunnel floor per step dwarfs the ~0.1 ms of compute
        (PROFILE.md) — so the K-step window amortizes the floor to 1/K.
        XLA sees a static K-iteration loop: weights stay resident in HBM
        for the whole window, no host round-trips between updates."""
        core = self._step_core()

        def multi(params, upd_state, net_state, it0, xs, ys, rngs):
            def body(carry, inp):
                params, upd_state, net_state, it = carry
                x, y, rng = inp
                params, upd_state, net_state, loss, _ = core(
                    params, upd_state, net_state, it, x, y, rng,
                    None, None, None)
                return (params, upd_state, net_state, it + 1.0), loss

            (params, upd_state, net_state, _), losses = jax.lax.scan(
                body, (params, upd_state, net_state, it0), (xs, ys, rngs))
            return params, upd_state, net_state, losses

        return instrument(jax.jit(multi, donate_argnums=(0, 1, 2)),
                          "MultiLayerNetwork.scanned_step",
                          argnums=(3, 4, 5, 6))

    def fit_scanned(self, batches, scan_steps: int, epochs: int = 1):
        """Amortized training: consecutive same-shape minibatches are
        stacked ``scan_steps`` at a time and run as one scanned XLA program
        (see ``_make_scanned_step``).  Semantically identical to ``fit``
        over the same batches (same per-batch updates and RNG stream);
        listeners fire once per window, ``score_value`` is the window's
        last loss.  A short tail (< scan_steps batches, or a shape change)
        runs through the regular per-batch step.  SGD only — no masks,
        TBPTT, or solver paths."""
        if scan_steps < 1:
            raise ValueError(f"scan_steps={scan_steps} must be >= 1")
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            raise ValueError("fit_scanned requires SGD optimization")
        if self.conf.backprop_type == "truncated_bptt":
            raise ValueError("fit_scanned does not support TBPTT")
        if self.conf.num_iterations != 1:
            # fit() repeats each batch num_iterations times; the scan body
            # runs each batch once — diverging silently would betray the
            # 'semantically identical to fit' promise above
            raise ValueError("fit_scanned requires num_iterations == 1 "
                             f"(got {self.conf.num_iterations})")
        if self.conf.introspection is not None:
            from deeplearning4j_tpu.observability import introspection

            introspection.ensure_state(self)
            self._introspect_live = None
        if self.conf.numerics is not None:
            from deeplearning4j_tpu.observability import numerics

            numerics.ensure_state(self)
            self._numerics_live = None
        scanned = self._jit_cache.setdefault(
            "scanned_step", self._make_scanned_step())
        step = self._get_train_step()
        try:
            for _ in range(epochs):
                window: list = []
                for batch in batches:
                    x, y, fm, lm = self._unpack(batch)
                    if fm is not None or lm is not None:
                        raise ValueError("fit_scanned does not support masks")
                    x, y = np.asarray(x), np.asarray(y)
                    if window and (window[0][0].shape != x.shape
                                   or window[0][1].shape != y.shape):
                        self._flush_window(window, scanned, step, scan_steps)
                        window = []
                    window.append((x, y))
                    if len(window) == scan_steps:
                        self._flush_window(window, scanned, step, scan_steps)
                        window = []
                if window:
                    self._flush_window(window, scanned, step, scan_steps)
        except Exception as e:
            crash_dump("fit_exception", model="MultiLayerNetwork",
                       iteration=self.iteration, error=repr(e))
            raise
        return self

    def _flush_window(self, window, scanned, step, scan_steps):
        if len(window) == scan_steps:
            tel = fit_telemetry("MultiLayerNetwork")
            t0 = time.perf_counter()
            with step_guard("fit_window", model="MultiLayerNetwork",
                            iteration=self.iteration, steps=len(window)):
                with tel.span(self.iteration):
                    xs = jnp.asarray(np.stack([b[0] for b in window]))
                    ys = jnp.asarray(np.stack([b[1] for b in window]))
                    rngs = jnp.stack([self._keys.next() for _ in window])
                    it0 = jnp.asarray(self.iteration, jnp.float32)
                    (self.params, self.updater_state, self.net_state,
                     losses) = scanned(self.params, self.updater_state,
                                       self.net_state, it0, xs, ys, rngs)
            self.score_value = losses[-1]
            self.iteration += len(window)
            tel.record_step(time.perf_counter() - t0, len(window[0][0]),
                            losses[-1], steps=len(window), model=self)
            # listeners fire once per window, so they get the WINDOW's
            # sample count — samples/sec = samples / (window wall time)
            notify_listeners(self, len(window[0][0]) * len(window))
        else:   # short tail: regular per-batch step keeps semantics exact
            for x, y in window:
                self._one_step(step, x, y, None, None, carries=None)

    def _get_train_step(self, with_carry=False):
        key = ("train_step", with_carry)
        if key not in self._jit_cache:
            self._jit_cache[key] = self._make_train_step(with_carry)
        return self._jit_cache[key]

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, *, fmask=None, lmask=None,
            epochs: int = 1, checkpoint_manager=None, retry_policy=None):
        """Train.  ``data`` is a DataSetIterator-style iterable of
        (features, labels[, fmask, lmask]) tuples, or a single (X, y) pair.
        Reference: ``MultiLayerNetwork.fit(DataSetIterator)`` :1029.

        With ``checkpoint_manager=`` the loop auto-resumes from the newest
        committed checkpoint (params/updater/RNG/iteration restored, the
        already-consumed batches skipped), saves on the manager's triggers
        at step boundaries, and — on SIGTERM/SIGINT via an installed
        ``PreemptionHandler`` — commits a priority checkpoint and returns
        cleanly.  ``retry_policy=`` retries transient step failures with
        backoff (docs/resilience.md)."""
        from deeplearning4j_tpu.observability import profiling, shardstats

        prof = profiling.active_profiler()
        if prof is not None:
            # memory attribution: flight/watchdog dumps show this model's
            # per-leaf param/updater byte breakdown (weakly held)
            prof.track_model(self, "MultiLayerNetwork")
        # sharding ledger (per-tree bytes/replication; metadata walk only,
        # once per fit call) — flight dumps and GET /memory read it
        shardstats.record_model_ledger(self, "MultiLayerNetwork")
        res = None
        if checkpoint_manager is not None or retry_policy is not None:
            from deeplearning4j_tpu.resilience import FitResilience

            res = FitResilience("MultiLayerNetwork", checkpoint_manager,
                                retry_policy, net=self)
        if self.conf.stability is not None:
            from deeplearning4j_tpu.resilience import stability

            stability.ensure_state(self)
            created = self._stab_rt is None
            if created:
                self._stab_rt = stability.StabilityRuntime(
                    "MultiLayerNetwork", self.conf.stability)
            if created or (res is not None and res.resumed_from is not None):
                # a restored nonfinite_total is history, not fresh evidence
                self._stab_rt.baseline_from(
                    self.updater_state.get(stability.STATE_KEY))
        if self.conf.introspection is not None:
            from deeplearning4j_tpu.observability import introspection

            introspection.ensure_state(self)
            # the facade's updater_state is authoritative during a solo
            # fit; a stale per-replica stamp from an earlier master run
            # must not shadow it
            self._introspect_live = None
        if self.conf.numerics is not None:
            from deeplearning4j_tpu.observability import numerics

            numerics.ensure_state(self)
            self._numerics_live = None
        try:
            if labels is not None:
                batches = [(data, labels, fmask, lmask)]
                self._fit_batches(batches, res)
                return self
            for _ in range(epochs):
                if self._fit_batches(data, res):
                    break   # preemption: stopped cleanly at a boundary
        except Exception as e:
            # fit-loop exception: leave the same flight-recorder report a
            # hang would (events + live spans + registry snapshot)
            crash_dump("fit_exception", model="MultiLayerNetwork",
                       iteration=self.iteration, error=repr(e))
            raise
        finally:
            if self._stab_rt is not None:
                # final harvest: the tail of the run past the last check
                # boundary still lands in the non-finite counter (early
                # stopping and health rules read it)
                self._stab_rt.flush(self)
        return self

    def _fit_batches(self, batches, res=None) -> bool:
        """One pass; returns True when preemption stopped the loop."""
        from deeplearning4j_tpu.resilience import preemption_requested

        if self.conf.optimization_algo != "stochastic_gradient_descent":
            for batch in batches:
                # the solver writes params/score and advances the iteration
                # by exactly 1 per batch, all AFTER the solve — so skip is
                # per batch and a whole-batch retry is state-safe
                if res is not None and res.skip_batch():
                    continue
                if preemption_requested():
                    if res is not None:
                        res.on_preempt(self)
                    return True
                x, y, fm, lm = self._unpack(batch)
                if res is not None:
                    res.step(lambda: self._fit_solver(x, y, fm, lm),
                             self.iteration, net=self)
                    res.after_step(self)
                else:
                    self._fit_solver(x, y, fm, lm)
            return False
        step = self._get_train_step()
        tbptt = self.conf.backprop_type == "truncated_bptt"
        L = self.conf.tbptt_fwd_length
        for batch in batches:
            x, y, fm, lm = self._unpack(batch)
            if res is not None:
                # skip is counted in ITERATIONS: one batch advances by
                # num_iterations, times the TBPTT window count for
                # sequence fits
                windows = -(-int(np.shape(x)[1]) // L) if tbptt else 1
                if res.skip_window(self.conf.num_iterations * windows):
                    continue
            if preemption_requested():
                if res is not None:
                    res.on_preempt(self)
                return True
            for _ in range(self.conf.num_iterations):
                if tbptt:
                    self._fit_tbptt(step, x, y, fm, lm, res)
                elif res is not None:
                    res.step(lambda: self._one_step(
                        step, x, y, fm, lm, carries=None),
                        self.iteration, net=self)
                else:
                    self._one_step(step, x, y, fm, lm, carries=None)
            if res is not None:
                res.after_step(self)
            if self._stab_rt is not None:
                # divergence sentinel: no-op except every check_every-th
                # boundary, where the device counter is harvested and an
                # escalation (LR backoff / checkpoint rewind) may land
                self._stab_rt.poll_net(self, res)
        return False

    def _fit_solver(self, x, y, fm, lm):
        """Full-batch solver path (CG/LBFGS/line-search GD) over the flat
        param vector.  Reference ``Solver.java:47-74`` dispatch +
        ``BaseOptimizer.java:165`` iterative optimize."""
        from deeplearning4j_tpu.optimize import solvers as solvers_mod

        args = (
            self.net_state, jnp.asarray(x), jnp.asarray(y), self._keys.next(),
            None if fm is None else jnp.asarray(fm),
            None if lm is None else jnp.asarray(lm),
        )

        def loss_fn(params, net_state, x, y, rng, fm, lm):
            return self._loss_fn(params, net_state, x, y, rng, fm, lm, None)

        solvers_mod.fit_model_with_solver(
            self, loss_fn, args, self.conf.optimization_algo,
            self.conf.num_iterations,
        )

    def _one_step(self, step, x, y, fm, lm, carries):
        from deeplearning4j_tpu.resilience import get_fault_injector

        inj = get_fault_injector()
        if inj is not None and inj.has_poison():
            # deterministic chaos: single-device fit loops poison under
            # worker id "0" (docs/resilience.md "Stability")
            x, y = inj.poison_batch("0", self.iteration, x, y)
        rng = self._keys.next()
        it = jnp.asarray(self.iteration, jnp.float32)
        tel = fit_telemetry("MultiLayerNetwork")
        t0 = time.perf_counter()
        with step_guard("fit_step", model="MultiLayerNetwork",
                        iteration=self.iteration):
            with tel.span(self.iteration):
                (self.params, self.updater_state, self.net_state, loss,
                 new_carries) = step(
                    self.params, self.updater_state, self.net_state, it,
                    jnp.asarray(x), jnp.asarray(y), rng,
                    None if fm is None else jnp.asarray(fm),
                    None if lm is None else jnp.asarray(lm),
                    carries,
                )
        self.score_value = loss  # device scalar; fetched lazily on read
        self.iteration += 1
        tel.record_step(time.perf_counter() - t0, int(np.shape(x)[0]), loss,
                        model=self)
        notify_listeners(self, int(np.shape(x)[0]))
        return new_carries

    def _fit_tbptt(self, step, x, y, fm, lm, res=None):
        """Truncated BPTT: slice the time axis into fwd-length windows,
        carrying RNN state (detached) across windows.
        Reference ``doTruncatedBPTT`` ``MultiLayerNetwork.java:1176``.

        The resilience retry scope is per WINDOW (each window is one
        iteration that already updated params — retrying a whole batch
        would replay committed windows)."""
        T = x.shape[1]
        L = self.conf.tbptt_fwd_length
        carries = None
        for t0 in range(0, T, L):
            sl = slice(t0, min(t0 + L, T))

            def one_window(c=carries, sl=sl):
                return self._one_step(
                    step, x[:, sl], y[:, sl],
                    None if fm is None else fm[:, sl],
                    None if lm is None else lm[:, sl],
                    c,
                )

            if res is not None:
                carries = res.step(one_window, self.iteration, net=self)
            else:
                carries = one_window()
            carries = jax.lax.stop_gradient(carries)

    @staticmethod
    def _unpack(batch):
        if isinstance(batch, (tuple, list)):
            if len(batch) == 2:
                return batch[0], batch[1], None, None
            if len(batch) == 4:
                return batch
        if hasattr(batch, "features"):
            return batch.features, batch.labels, getattr(batch, "features_mask", None), getattr(batch, "labels_mask", None)
        raise ValueError(f"Cannot unpack batch of type {type(batch)}")

    # ------------------------------------------------------------- inference
    def _output_fn(self):
        if "output" not in self._jit_cache:

            def out(params, net_state, x, fmask):
                pre, _, _, _ = self._forward(params, net_state, x, train=False,
                                             rng=None, fmask=fmask)
                if self.conf.compute_dtype is not None:
                    pre = pre.astype(jnp.float32)  # fp32 API boundary
                from deeplearning4j_tpu.nn import activations

                return activations.get(self.layers[-1].activation)(pre)

            self._jit_cache["output"] = jax.jit(out)
        return self._jit_cache["output"]

    def output(self, x, fmask=None):
        """Inference forward (reference ``output`` :1525-1607, TEST mode)."""
        return self._output_fn()(self.params, self.net_state, jnp.asarray(x),
                                 None if fmask is None else jnp.asarray(fmask))

    def feed_forward(self, x, train: bool = False):
        """All layer activations (reference ``feedForward`` :619-688)."""
        rng = self._keys.next() if train else None
        pre, acts, _, _ = self._forward(self.params, self.net_state,
                                        jnp.asarray(x), train=train, rng=rng,
                                        collect=True)
        if self.conf.compute_dtype is not None:
            acts = [a.astype(jnp.float32) for a in acts]  # fp32 API boundary
        return acts

    def evaluate(self, iterator, evaluation=None):
        """Run the iterator through ``output`` and accumulate classification
        metrics (reference ``MultiLayerNetwork.evaluate(DataSetIterator)``)."""
        from deeplearning4j_tpu.evaluation import Evaluation

        ev = evaluation or Evaluation()
        for ds in iterator:
            out = self.output(ds.features, fmask=ds.features_mask)
            ev.eval(ds.labels, out, mask=ds.labels_mask)
        return ev

    def score(self, x=None, y=None, dataset=None, fmask=None, lmask=None) -> float:
        if dataset is not None:
            if hasattr(dataset, "features"):
                x, y = dataset.features, dataset.labels
                fmask = fmask if fmask is not None else getattr(dataset, "features_mask", None)
                lmask = lmask if lmask is not None else getattr(dataset, "labels_mask", None)
            else:
                x, y = dataset[0], dataset[1]
        loss, _ = self._loss_fn(self.params, self.net_state, jnp.asarray(x),
                                jnp.asarray(y), None, fmask, lmask, train=False)
        return float(loss)

    # ------------------------------------------------- streaming rnnTimeStep
    def rnn_clear_previous_state(self):
        self._rnn_state = {}
        self._stream_pos = 0

    def _embeds_ids(self) -> bool:
        """First layer consumes integer token ids (EmbeddingLayer), so a
        rank-2 streaming input is [B, T] ids, not [B, F] features."""
        from deeplearning4j_tpu.nn.layers.dense import EmbeddingLayer

        return bool(self.layers) and isinstance(self.layers[0], EmbeddingLayer)

    def rnn_time_step(self, x):
        """Stateful streaming inference (reference ``rnnTimeStep`` :2195):
        feeds one (or a few) timesteps, carries hidden state between calls.
        Recurrent layers carry hidden state; attention layers carry a KV
        cache (seeded on first call), so transformer stacks stream through
        the same API as LSTMs."""
        from deeplearning4j_tpu.models.common import (
            check_cache_capacity, seed_stream_caches,
        )

        x = jnp.asarray(x)
        if self._embeds_ids():
            collapse = self.layers[0].collapse_column
            # [B] ids are one timestep; with column semantics, so is [B, 1]
            # (the reference's column-of-indices form, which the old
            # streaming contract returned as [B, V])
            squeeze = x.ndim == 1 or (
                collapse and x.ndim == 2 and x.shape[1] == 1)
            if x.ndim == 1:
                x = x[:, None]
            if x.ndim == 2 and collapse:
                # [B, T, 1] keeps the time axis unambiguous for embeddings
                # that collapse a trailing 1 as a column-of-indices
                x = x[..., None]
        else:
            squeeze = x.ndim == 2          # [B, F]: one timestep of features
            if squeeze:
                x = x[:, None, :]
        if not self._rnn_state:
            self._stream_pos = 0
        carries = seed_stream_caches(
            ((l.name, l) for l in self.layers), self._rnn_state,
            x.shape[0], self.conf.compute_dtype)
        # host-side position counter: no device->host sync per streamed chunk
        check_cache_capacity(carries, int(x.shape[1]), pos=self._stream_pos)
        carries = carries or None
        pre, _, _, new_carries = self._forward(
            self.params, self.net_state, x, train=False, rng=None, carries=carries
        )
        self._rnn_state = new_carries
        self._stream_pos += int(x.shape[1])
        from deeplearning4j_tpu.nn import activations

        out = activations.get(self.layers[-1].activation)(pre)
        return out[:, -1] if squeeze and out.ndim == 3 else out

    # ------------------------------------------------------------- pretrain
    def pretrain(self, batches, epochs: int = 1):
        """Layerwise unsupervised pretraining (reference ``pretrain``
        ``MultiLayerNetwork.java:164``; RBM/AutoEncoder objectives)."""
        from deeplearning4j_tpu.nn.layers.autoencoder import AutoEncoder, RBM

        batches = list(batches) if not isinstance(batches, list) else batches
        for i, layer in enumerate(self.layers):
            if not isinstance(layer, (AutoEncoder, RBM)):
                continue

            def ploss(lparams, x, rng, _layer=layer):
                return _layer.pretrain_loss(lparams, x, rng)

            grad_fn = jax.jit(jax.value_and_grad(ploss))
            lr = layer.learning_rate or self.conf.updater.learning_rate
            for _ in range(epochs):
                for batch in batches:
                    # bare feature arrays are fine here: pretraining is
                    # unsupervised, labels are ignored even when present
                    x = jnp.asarray(batch if hasattr(batch, "ndim")
                                    else self._unpack(batch)[0])
                    # feed through earlier layers (test mode)
                    for j in range(i):
                        if j in self.conf.preprocessors:
                            x = self.conf.preprocessors[j](x)
                        x, _ = self.layers[j].apply(
                            self.params[self.layers[j].name],
                            self.net_state.get(self.layers[j].name, {}),
                            x, train=False, rng=None,
                        )
                    if i in self.conf.preprocessors:
                        x = self.conf.preprocessors[i](x)
                    loss, g = grad_fn(self.params[layer.name], x, self._keys.next())
                    self.params[layer.name] = jax.tree_util.tree_map(
                        lambda p, gg: p - lr * gg, self.params[layer.name], g
                    )
        return self

    # ------------------------------------------------------------- listeners
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    # ------------------------------------------------------------------ io
    def save(self, path, save_updater: bool = True):
        from deeplearning4j_tpu.models import serialization

        serialization.write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path) -> "MultiLayerNetwork":
        from deeplearning4j_tpu.models import serialization

        return serialization.restore_multi_layer_network(path)

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(self.conf)
        net.params = jax.tree_util.tree_map(lambda a: a, self.params)
        net.net_state = jax.tree_util.tree_map(lambda a: a, self.net_state)
        net.updater_state = jax.tree_util.tree_map(lambda a: a, self.updater_state)
        net.iteration = self.iteration
        return net
