"""Model checkpointing — zip container with JSON config + binary params.

Reference: ``util/ModelSerializer.java:32-95``: a zip holding
``configuration.json`` + ``coefficients.bin`` (flattened params) +
``updaterState.bin``.  Same container here (plus ``netState.npz`` for BN
running stats and a manifest), so the capability — one portable file,
config round-trip, resume with optimizer state — is identical.  Large-scale
mesh-sharded checkpoints (per-host shard files, resumable, any-mesh
restore) live in ``parallel/checkpoint.py``; this single-file format is the
ModelSerializer-parity path.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

FORMAT_VERSION = 1
CONFIG_ENTRY = "configuration.json"
COEFFICIENTS_ENTRY = "coefficients.npz"
UPDATER_ENTRY = "updaterState.npz"
NET_STATE_ENTRY = "netState.npz"
MANIFEST_ENTRY = "manifest.json"


def _tree_to_npz_bytes(tree) -> bytes:
    flat = {}

    def visit(path, leaf):
        key = "/".join(str(p) for p in path)
        flat[key] = np.asarray(leaf)

    _walk(tree, (), visit)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    return buf.getvalue()


def _walk(tree, path, visit):
    if isinstance(tree, dict):
        for k in sorted(tree):
            _walk(tree[k], path + (k,), visit)
    elif isinstance(tree, (tuple, list)):
        for i, v in enumerate(tree):
            _walk(v, path + (i,), visit)
    elif tree is not None:
        visit(path, tree)


def _npz_bytes_to_flat(data: bytes) -> Dict[str, np.ndarray]:
    return dict(np.load(io.BytesIO(data)))


def _restore_like(template, flat: Dict[str, np.ndarray], path=()):
    """Rebuild a pytree with the template's structure from flat npz entries."""
    if isinstance(template, dict):
        return {k: _restore_like(v, flat, path + (k,)) for k, v in template.items()}
    if isinstance(template, (tuple, list)):
        seq = [_restore_like(v, flat, path + (i,)) for i, v in enumerate(template)]
        return tuple(seq) if isinstance(template, tuple) else seq
    if template is None:
        return None
    key = "/".join(str(p) for p in path)
    return jnp.asarray(flat[key])


def write_model(net, path, save_updater: bool = True,
                extra_manifest: Dict[str, Any] = None) -> None:
    """``extra_manifest`` entries are merged into the manifest (reserved
    keys rejected) — e.g. ``{"serving_version": 7}`` pins the version a
    serving ``ModelRegistry`` assigns this checkpoint on hot-swap."""
    manifest: Dict[str, Any] = {
        "format_version": FORMAT_VERSION,
        "model_type": type(net).__name__,
        "iteration": net.iteration,
        "framework": "deeplearning4j_tpu",
    }
    if extra_manifest:
        clash = set(extra_manifest) & set(manifest)
        if clash:
            raise ValueError(f"extra_manifest may not override {sorted(clash)}")
        manifest.update(extra_manifest)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(MANIFEST_ENTRY, json.dumps(manifest))
        zf.writestr(CONFIG_ENTRY, net.conf.to_json())
        zf.writestr(COEFFICIENTS_ENTRY, _tree_to_npz_bytes(net.params))
        if net.net_state:
            zf.writestr(NET_STATE_ENTRY, _tree_to_npz_bytes(net.net_state))
        if save_updater and net.updater_state:
            zf.writestr(UPDATER_ENTRY, _tree_to_npz_bytes(net.updater_state))


def read_manifest(path) -> Dict[str, Any]:
    """The checkpoint's manifest dict without loading any weights."""
    with zipfile.ZipFile(path, "r") as zf:
        return json.loads(zf.read(MANIFEST_ENTRY).decode())


def load_model(path, load_updater: bool = True):
    """Generic restore dispatching on the manifest's model_type
    (≙ ``ModelSerializer.restoreMultiLayerNetwork``/``restoreComputationGraph``
    pair, but format-self-describing)."""
    mtype = read_manifest(path).get("model_type")
    if mtype == "MultiLayerNetwork":
        return restore_multi_layer_network(path, load_updater)
    if mtype == "ComputationGraph":
        return restore_computation_graph(path, load_updater)
    raise ValueError(f"Unknown model_type '{mtype}' in {path}")


def restore_multi_layer_network(path, load_updater: bool = True):
    from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    with zipfile.ZipFile(path, "r") as zf:
        conf = MultiLayerConfiguration.from_json(zf.read(CONFIG_ENTRY).decode())
        net = MultiLayerNetwork(conf).init()
        names = set(zf.namelist())
        coeff = _npz_bytes_to_flat(zf.read(COEFFICIENTS_ENTRY))
        net.params = _restore_like(net.params, coeff)
        if NET_STATE_ENTRY in names:
            net.net_state = _restore_like(net.net_state, _npz_bytes_to_flat(zf.read(NET_STATE_ENTRY)))
        if load_updater and UPDATER_ENTRY in names:
            net.updater_state = _restore_like(net.updater_state, _npz_bytes_to_flat(zf.read(UPDATER_ENTRY)))
        manifest = json.loads(zf.read(MANIFEST_ENTRY).decode())
        net.iteration = manifest.get("iteration", 0)
    return net


def restore_computation_graph(path, load_updater: bool = True):
    from deeplearning4j_tpu.models.graph import ComputationGraph
    from deeplearning4j_tpu.models.graph import GraphConfiguration

    with zipfile.ZipFile(path, "r") as zf:
        conf = GraphConfiguration.from_json(zf.read(CONFIG_ENTRY).decode())
        net = ComputationGraph(conf).init()
        names = set(zf.namelist())
        coeff = _npz_bytes_to_flat(zf.read(COEFFICIENTS_ENTRY))
        net.params = _restore_like(net.params, coeff)
        if NET_STATE_ENTRY in names:
            net.net_state = _restore_like(net.net_state, _npz_bytes_to_flat(zf.read(NET_STATE_ENTRY)))
        if load_updater and UPDATER_ENTRY in names:
            net.updater_state = _restore_like(net.updater_state, _npz_bytes_to_flat(zf.read(UPDATER_ENTRY)))
        manifest = json.loads(zf.read(MANIFEST_ENTRY).decode())
        net.iteration = manifest.get("iteration", 0)
    return net
