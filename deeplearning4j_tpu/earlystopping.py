"""Early stopping: config-driven train-until-done.

Reference: ``deeplearning4j-nn/.../earlystopping/`` —
``EarlyStoppingConfiguration.java:45-71`` (builder: modelSaver, epoch/iteration
termination conditions, saveLastModel, evaluateEveryNEpochs, scoreCalculator),
``trainer/BaseEarlyStoppingTrainer.java:76-147`` (epoch loop: fit every batch,
check per-iteration conditions on model score, every-N-epochs compute the
validation score, track/save best model, check epoch conditions),
``termination/*.java``, ``saver/{InMemoryModelSaver,LocalFileModelSaver}.java``,
``scorecalc/DataSetLossCalculator.java``.

Works for both MultiLayerNetwork and ComputationGraph (anything exposing
``fit(DataSet)``, ``score``, ``save/load`` and ``clone``).
"""

from __future__ import annotations

import copy
import enum
import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


# ---------------------------------------------------------------------------
# termination conditions
# ---------------------------------------------------------------------------

class IterationTerminationCondition:
    """Checked after every minibatch against the last minibatch score."""

    def initialize(self) -> None:  # pragma: no cover - trivial
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class EpochTerminationCondition:
    """Checked at the end of each (evaluated) epoch against validation score."""

    def initialize(self) -> None:  # pragma: no cover - trivial
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    """≙ ``MaxEpochsTerminationCondition.java``."""

    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float) -> bool:
        return epoch + 1 >= self.max_epochs

    def __repr__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop when score hasn't improved (by > min_improvement) for
    ``patience`` epochs. ≙ ``ScoreImprovementEpochTerminationCondition.java``."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement

    def initialize(self) -> None:
        self.best_score: Optional[float] = None
        self.epochs_since_improvement = 0

    def terminate(self, epoch: int, score: float) -> bool:
        if self.best_score is None:
            self.best_score = score
            return False
        if self.best_score - score > self.min_improvement:
            self.best_score = score
            self.epochs_since_improvement = 0
            return False
        self.epochs_since_improvement += 1
        return self.epochs_since_improvement >= self.patience

    def __repr__(self):
        return (f"ScoreImprovementEpochTerminationCondition(patience="
                f"{self.patience}, minImprovement={self.min_improvement})")


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop as soon as the score drops below a target ("good enough").
    ≙ ``BestScoreEpochTerminationCondition.java``."""

    def __init__(self, best_expected_score: float):
        self.best_expected_score = best_expected_score

    def terminate(self, epoch: int, score: float) -> bool:
        return score < self.best_expected_score

    def __repr__(self):
        return f"BestScoreEpochTerminationCondition({self.best_expected_score})"


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    """Wall-clock cutoff. ≙ ``MaxTimeIterationTerminationCondition.java``."""

    def __init__(self, max_time_seconds: float):
        self.max_time_seconds = max_time_seconds
        self.start = time.time()

    def initialize(self) -> None:
        self.start = time.time()

    def terminate(self, last_score: float) -> bool:
        return (time.time() - self.start) > self.max_time_seconds

    def __repr__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_time_seconds}s)"


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Terminate if score exceeds a ceiling (divergence guard).
    ≙ ``MaxScoreIterationTerminationCondition.java``."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score: float) -> bool:
        return last_score > self.max_score

    def __repr__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """NaN/Inf guard. ≙ ``InvalidScoreIterationTerminationCondition.java``.

    Besides the classic last-score check, this condition watches the
    stability engine's device-side non-finite counter
    (``dl4j_nonfinite_steps_total``, resilience/stability.py): with the
    step guard on, a poisoned step keeps the params finite and the lazy
    score gauge may never be polled while NaN — the counter catches it
    anyway.  The baseline is taken at ``initialize()`` so only
    non-finite steps observed DURING this early-stopping run terminate
    it.  Counter harvest happens at the engine's ``check_every``
    boundaries (and at fit exit), so detection latency is bounded by
    that cadence.

    ``component=`` narrows the watched counter children (the family is
    labeled per component: ``"MultiLayerNetwork"`` /
    ``"ComputationGraph"`` / the master names) — set it when OTHER
    stability-enabled runs share the process (an online pipeline, a side
    model), or their skipped steps would terminate this run too.  The
    default watches every component, which is correct for the common
    one-training-run-per-process deployment."""

    def __init__(self, component: Optional[str] = None):
        self.component = component
        self._baseline: Optional[float] = None

    def _nonfinite_total(self) -> float:
        from deeplearning4j_tpu.observability import get_registry

        labels = {"component": self.component} if self.component else {}
        return get_registry().family_total("dl4j_nonfinite_steps_total",
                                           **labels)

    def initialize(self) -> None:
        self._baseline = self._nonfinite_total()

    def terminate(self, last_score: float) -> bool:
        if math.isnan(last_score) or math.isinf(last_score):
            return True
        base = self._baseline if self._baseline is not None else 0.0
        return self._nonfinite_total() > base

    def __repr__(self):
        if self.component:
            return (f"InvalidScoreIterationTerminationCondition("
                    f"component={self.component!r})")
        return "InvalidScoreIterationTerminationCondition()"


# ---------------------------------------------------------------------------
# model savers
# ---------------------------------------------------------------------------

class EarlyStoppingModelSaver:
    def save_best_model(self, net, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, net, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(EarlyStoppingModelSaver):
    """≙ ``saver/InMemoryModelSaver.java`` — keeps clones in RAM."""

    def __init__(self):
        self.best = None
        self.latest = None

    def save_best_model(self, net, score: float) -> None:
        self.best = net.clone()

    def save_latest_model(self, net, score: float) -> None:
        self.latest = net.clone()

    def get_best_model(self):
        return self.best

    def get_latest_model(self):
        return self.latest


class LocalFileModelSaver(EarlyStoppingModelSaver):
    """≙ ``saver/LocalFileModelSaver.java`` — bestModel.zip / latestModel.zip
    in a directory, restored through the model's own serializer."""

    def __init__(self, directory: str, model_cls=None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._model_cls = model_cls

    @property
    def best_path(self) -> str:
        return os.path.join(self.directory, "bestModel.zip")

    @property
    def latest_path(self) -> str:
        return os.path.join(self.directory, "latestModel.zip")

    def _save_atomic(self, net, path: str) -> None:
        # stage + rename: a crash mid-write must never tear the PREVIOUS
        # best/latest model (the reference rewrites the zip in place)
        tmp = path + ".tmp"
        net.save(tmp)
        os.replace(tmp, path)

    def save_best_model(self, net, score: float) -> None:
        self._model_cls = self._model_cls or type(net)
        self._save_atomic(net, self.best_path)

    def save_latest_model(self, net, score: float) -> None:
        self._model_cls = self._model_cls or type(net)
        self._save_atomic(net, self.latest_path)

    def _load(self, path):
        if self._model_cls is not None:
            return self._model_cls.load(path)
        from deeplearning4j_tpu.models import serialization

        return serialization.load_model(path)

    def get_best_model(self):
        if not os.path.exists(self.best_path):
            return None
        return self._load(self.best_path)

    def get_latest_model(self):
        if not os.path.exists(self.latest_path):
            return None
        return self._load(self.latest_path)


class CheckpointModelSaver(EarlyStoppingModelSaver):
    """Model saving routed through ``resilience.CheckpointManager``: every
    best/latest save commits atomically (tmp -> fsync -> rename + COMMIT)
    and retention is bounded to ``keep`` checkpoints per track — replacing
    ad-hoc ``save_checkpoint`` call sites that wrote non-atomically into a
    live directory and retained forever.  ``get_*_model`` restores into a
    clone of the last-saved net (params, updater state, RNG stream and
    iteration all come from the checkpoint), so a crash between epochs
    loses at most the uncommitted epoch."""

    def __init__(self, directory: str, keep: int = 2):
        from deeplearning4j_tpu.resilience import CheckpointManager

        self.directory = directory
        # synchronous managers: an early-stopping epoch boundary is not a
        # hot loop, and the trainer reads the model back immediately
        self._best = CheckpointManager(
            os.path.join(directory, "best"), keep=keep, async_save=False,
            auto_resume=False)
        self._latest = CheckpointManager(
            os.path.join(directory, "latest"), keep=keep, async_save=False,
            auto_resume=False)
        self._template = None

    def save_best_model(self, net, score: float) -> None:
        self._template = net
        self._best.save(net, trigger="best")

    def save_latest_model(self, net, score: float) -> None:
        self._template = net
        self._latest.save(net, trigger="latest")

    def _restore_from(self, manager):
        if self._template is None or manager.latest() is None:
            return None
        model = self._template.clone()
        manager.restore(model)
        return model

    def get_best_model(self):
        return self._restore_from(self._best)

    def get_latest_model(self):
        return self._restore_from(self._latest)


# ---------------------------------------------------------------------------
# score calculators
# ---------------------------------------------------------------------------

class ScoreCalculator:
    def calculate_score(self, net) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a validation iterator.
    ≙ ``scorecalc/DataSetLossCalculator.java`` (average=True semantics)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        self.iterator.reset()
        total, n = 0.0, 0
        for ds in self.iterator:
            b = ds.num_examples()
            total += net.score(dataset=ds) * b
            n += b
        return total / n if self.average and n else total


# ---------------------------------------------------------------------------
# configuration / result / trainer
# ---------------------------------------------------------------------------

class TerminationReason(enum.Enum):
    ERROR = "Error"
    ITERATION_TERMINATION_CONDITION = "IterationTerminationCondition"
    EPOCH_TERMINATION_CONDITION = "EpochTerminationCondition"


@dataclass
class EarlyStoppingResult:
    """≙ ``EarlyStoppingResult.java``."""

    termination_reason: TerminationReason
    termination_details: str
    score_vs_epoch: Dict[int, float]
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any


@dataclass
class EarlyStoppingConfiguration:
    """≙ ``EarlyStoppingConfiguration.java`` builder fields."""

    model_saver: EarlyStoppingModelSaver = field(default_factory=InMemoryModelSaver)
    epoch_termination_conditions: List[EpochTerminationCondition] = field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = field(default_factory=list)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1
    score_calculator: Optional[ScoreCalculator] = None

    class Builder:
        def __init__(self):
            self._cfg = EarlyStoppingConfiguration()

        def model_saver(self, saver):
            self._cfg.model_saver = saver
            return self

        def epoch_termination_conditions(self, *conds):
            self._cfg.epoch_termination_conditions = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._cfg.iteration_termination_conditions = list(conds)
            return self

        def save_last_model(self, b: bool = True):
            self._cfg.save_last_model = b
            return self

        def evaluate_every_n_epochs(self, n: int):
            self._cfg.evaluate_every_n_epochs = n
            return self

        def score_calculator(self, sc):
            self._cfg.score_calculator = sc
            return self

        def build(self):
            return self._cfg


class EarlyStoppingTrainer:
    """≙ ``trainer/BaseEarlyStoppingTrainer.java:76-147``: the epoch loop.

    Single implementation covers MLN and CG (reference has one subclass per
    facade; our facades share the fit/score/clone surface).
    """

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator,
                 listener: Optional[Any] = None):
        self.config = config
        self.net = net
        self.train = train_iterator
        self.listener = listener

    def _train_epoch(self, cfg):
        """One pass over the training iterator.  Returns (terminate, reason)
        from the iteration termination conditions."""
        for ds in self.train:
            if hasattr(ds, "features"):
                self.net.fit(ds.features, ds.labels,
                             fmask=getattr(ds, "features_mask", None),
                             lmask=getattr(ds, "labels_mask", None))
            else:
                x, y = ds[0], ds[1]
                self.net.fit(x, y)
            last_score = self.net.score_value
            for c in cfg.iteration_termination_conditions:
                if c.terminate(last_score):
                    return True, c
        return False, None

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        if self.listener is not None:
            self.listener.on_start(cfg, self.net)

        score_vs_epoch: Dict[int, float] = {}
        best_score = float("inf")
        best_epoch = -1
        epoch = 0
        while True:
            self.train.reset()
            terminate = False
            reason: Optional[IterationTerminationCondition] = None
            try:
                terminate, reason = self._train_epoch(cfg)
            except Exception as e:  # ≙ reference Error termination path
                result = EarlyStoppingResult(
                    TerminationReason.ERROR, repr(e), score_vs_epoch,
                    best_epoch, best_score, epoch,
                    cfg.model_saver.get_best_model())
                if self.listener is not None:
                    self.listener.on_completion(result)
                return result

            if terminate:
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.net, self.net.score_value)
                result = EarlyStoppingResult(
                    TerminationReason.ITERATION_TERMINATION_CONDITION,
                    repr(reason), score_vs_epoch, best_epoch, best_score,
                    epoch, cfg.model_saver.get_best_model())
                if self.listener is not None:
                    self.listener.on_completion(result)
                return result

            # every-N-epochs validation scoring; epoch termination conditions
            # are only checked on evaluated epochs so they never see a stale
            # or placeholder score (≙ evaluateEveryNEpochs gating in the
            # reference epoch loop)
            evaluate = (epoch == 0 or (epoch + 1) % cfg.evaluate_every_n_epochs == 0)
            score = 0.0
            if evaluate:
                if cfg.score_calculator is not None:
                    score = cfg.score_calculator.calculate_score(self.net)
                score_vs_epoch[epoch] = score
                if self.listener is not None:
                    self.listener.on_epoch(epoch, score, cfg, self.net)
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.save_best_model(self.net, score)
            if cfg.save_last_model:
                cfg.model_saver.save_latest_model(self.net, score)

            if evaluate:
                for c in cfg.epoch_termination_conditions:
                    if c.terminate(epoch, score):
                        result = EarlyStoppingResult(
                            TerminationReason.EPOCH_TERMINATION_CONDITION,
                            repr(c), score_vs_epoch, best_epoch, best_score,
                            epoch + 1, cfg.model_saver.get_best_model())
                        if self.listener is not None:
                            self.listener.on_completion(result)
                        return result
            epoch += 1


class EarlyStoppingListener:
    """≙ ``listener/EarlyStoppingListener.java`` hook surface."""

    def on_start(self, config, net) -> None:  # pragma: no cover - hook
        pass

    def on_epoch(self, epoch, score, config, net) -> None:  # pragma: no cover
        pass

    def on_completion(self, result) -> None:  # pragma: no cover - hook
        pass


class DistributedEarlyStoppingTrainer(EarlyStoppingTrainer):
    """Early stopping over mesh-distributed training.

    ≙ ``spark/dl4j-spark/.../earlystopping/BaseSparkEarlyStoppingTrainer.java``
    (fit an epoch through the Spark wrapper, score, check conditions) — here
    each epoch trains through the DistributedNetwork's TrainingMaster and the
    iteration conditions see the post-epoch score.
    """

    def __init__(self, config: EarlyStoppingConfiguration, dist_net,
                 train_iterator, listener: Optional[Any] = None):
        super().__init__(config, dist_net.net, train_iterator, listener)
        self.dist = dist_net

    def _train_epoch(self, cfg):
        self.dist.fit(self.train)
        last_score = self.net.score_value
        for c in cfg.iteration_termination_conditions:
            if c.terminate(last_score):
                return True, c
        return False, None
