"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A from-scratch JAX/XLA/Pallas re-design with the capability surface of
deeplearning4j (reference: arshadm/deeplearning4j @ 0.4-rc3.9): layer zoo,
fluent config DSL with JSON round-trip, Sequential (MultiLayerNetwork) and
Graph (ComputationGraph) facades, updater zoo, evaluation, early stopping,
checkpointing, data-parallel training over TPU meshes, NLP embeddings,
graph embeddings, clustering, and training observability.

Design (see SURVEY.md §7): a pure-functional core — layers are
``init``/``apply`` pairs over parameter pytrees, the train step is one jitted
pure function — wrapped by thin stateful facades that reproduce the
reference's API surface. Scale-out is in-graph XLA collectives over a
``jax.sharding.Mesh`` (ICI/DCN), not driver-centric parameter shipping.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.backend import device as backend
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.models.sequential import MultiLayerNetwork
