"""Flash attention as a Pallas TPU kernel (fwd + bwd custom VJP).

The helper-layer flagship for the transformer path: where the reference's
accelerated module fuses conv/pool/BN through cuDNN
(``deeplearning4j-cuda/.../CudnnConvolutionHelper.java:51``), the TPU
framework's memory-bound hot spot is attention — materialising the
``[B, H, T, T]`` score matrix in HBM is what caps sequence length.  This
kernel computes softmax(QK^T)V blockwise in VMEM with the online-softmax
recurrence (running row-max ``m`` and normaliser ``l``), so HBM traffic is
O(T·D) instead of O(T²), and the backward pass rematerialises attention
probabilities per block from the saved logsumexp instead of storing them.

Layouts follow the TPU tiling rules: blocks are (block_q|block_k, D) VMEM
tiles, the per-row statistics (m, l, logsumexp, delta) are carried
broadcast across a 128-lane minor dimension, and matmuls accumulate in
float32 via ``preferred_element_type`` regardless of input dtype (bf16
inputs ride the MXU at full rate).

Grid convention (sequential minor axis carries scratch):
  forward:  (B*H, nq, nk)  — k-axis 'arbitrary', acc/m/l scratch
  dq:       (B*H, nq, nk)  — k-axis 'arbitrary', dq scratch
  dk/dv:    (B*H, nk, nq)  — q-axis 'arbitrary', dk/dv scratch

On non-TPU backends the same kernels run ``interpret=True`` (CI parity);
`pytest -m tpu` exercises the compiled path on a real chip.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.helpers import interpret_mode as _interpret

LANES = 128
NEG_INF = -1e30

# jax-version seams (kernel-trust harness classifies these as
# reference-setup divergences, not kernel bugs — docs/observability.md):
# jax.typeof landed after 0.4.x; varying-mesh-axes metadata (vma) with it.
_typeof = getattr(jax, "typeof", None)
# the Pallas TPU params class was renamed TPUCompilerParams->CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def pick_blocks(t: int, block_q: Optional[int] = None,
                block_k: Optional[int] = None) -> Optional[tuple]:
    """Largest block sizes that tile T exactly, capped at the measured
    sweet spot (bq 512, bk 1024 but at most T/2, on v5e — bk == T leaves
    the sequential grid axis with a single step and measured ~5% slower at
    T=1024; see PROFILE.md).  Returns None when T has no usable tiling."""
    def pk(cap):
        # lane-multiple candidates only: the [bq, bk] score tile wants its
        # minor dim on 128-lane boundaries
        for b in (cap, cap // 2, cap // 4, cap // 8, 128):
            if b >= 128 and b % 128 == 0 and t % b == 0:
                return b
        return None

    bq = block_q or pk(512)
    bk = block_k or pk(min(1024, max(128, t // 2)))
    if bq is None or bk is None or t % bq or t % bk:
        return None
    return bq, bk


def supports(t: int, d: int, block_q: Optional[int] = None,
             block_k: Optional[int] = None) -> bool:
    """The fused path needs whole blocks along time (no tail masking in the
    kernel); head_dim is zero-padded to a lane multiple, which is exact."""
    return pick_blocks(t, block_q, block_k) is not None


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-mesh-axes set of ``like`` so
    the kernels also work inside ``shard_map`` (check_vma requires pallas
    out_shapes to declare how outputs vary — they vary like q does)."""
    vma = getattr(_typeof(like), "vma", None) if _typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _dot_f32(a, b, trans_a=False, trans_b=False):
    """dot_general with f32 accumulation; contraction picked by flags so we
    never pay an explicit transpose relayout inside the kernel."""
    ca = 0 if trans_a else 1
    cb = 1 if trans_b else 0
    return jax.lax.dot_general(
        a, b, (((ca,), (cb,)), ((), ())),
        preferred_element_type=jnp.float32)


def _causal_mask(s, qi, ki, block_q, block_k, window=None):
    """Causal (and optionally sliding-window banded) score masking by
    global position: keep kpos in [qpos - window + 1, qpos]."""
    qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    keep = qpos >= kpos
    if window is not None:
        keep &= kpos > qpos - window
    return jnp.where(keep, s, NEG_INF)


def _block_live(qi, ki, block_q, block_k, causal, window):
    """Does block (qi, ki) intersect the (banded) causal region?"""
    if not causal:
        return True
    live = qi * block_q + block_q - 1 >= ki * block_k
    if window is not None:
        live &= ki * block_k + block_k - 1 > qi * block_q - window
    return live


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, window,
                block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # blocks outside the (banded) causal region contribute nothing
    run = _block_live(qi, ki, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        s = _dot_f32(q_ref[:], k_ref[:], trans_b=True) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, window)
        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [bq, bk] f32
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + _dot_f32(
            p.astype(v_ref.dtype), v_ref[:])
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        # fully-masked rows (can't happen causally, but keep it NaN-safe)
        safe = jnp.where(l > 0, l, 1.0)
        o_ref[:] = (acc_scr[:] / safe).astype(o_ref.dtype)
        lse_ref[:] = m_scr[:] + jnp.log(safe)


def _kv_index(causal, block_q, block_k, window=None):
    """K/V block index for q-major grids.  Blocks outside the (banded)
    causal region clamp to the nearest live block: the index stops
    changing, so the Pallas pipeline skips their HBM->VMEM copies entirely
    (the compute for those steps is already skipped by the kernels'
    ``run`` predicate)."""
    if not causal:
        return lambda b, qi, ki: (b, ki, 0)

    def idx(b, qi, ki):
        hi = (qi * block_q + block_q - 1) // block_k
        k = jnp.minimum(ki, hi)
        if window is not None:
            lo = jnp.maximum(0, (qi * block_q - window + 1) // block_k)
            k = jnp.maximum(k, lo)
        return (b, k, 0)

    return idx


def _q_index(causal, block_q, block_k, window=None):
    """Q-side block index for the k-major (dk/dv) grid: q blocks outside
    the band clamp to the nearest live one."""
    if not causal:
        return lambda b, ki, qi: (b, qi, 0)

    def idx(b, ki, qi):
        lo = (ki * block_k) // block_q
        q = jnp.maximum(qi, lo)
        if window is not None:
            hi = (ki * block_k + block_k - 1 + window - 1) // block_q
            q = jnp.minimum(q, hi)
        return (b, q, 0)

    return idx


def _fwd_call(q, k, v, *, scale, causal, window, block_q, block_k,
              interpret):
    """q,k,v: [BH, T, D] (D already lane-padded). Returns (o, lse[BH,T,128])."""
    bh, t, d = q.shape
    nq, nk = t // block_q, t // block_k
    grid = (bh, nq, nk)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             window=window, block_q=block_q,
                             block_k=block_k)
    kv_idx = _kv_index(causal, block_q, block_k, window)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((None, block_k, d), kv_idx),
            pl.BlockSpec((None, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((None, block_q, LANES), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            _sds((bh, t, d), q.dtype, q),
            _sds((bh, t, LANES), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref,
               dq_scr, *, scale, causal, window, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = _block_live(qi, ki, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        s = _dot_f32(q_ref[:], k_ref[:], trans_b=True) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, window)
        p = jnp.exp(s - lse_ref[:, :1])                      # [bq, bk]
        dp = _dot_f32(do_ref[:], v_ref[:], trans_b=True)     # [bq, bk]
        ds = p * (dp - di_ref[:, :1])
        dq_scr[:] += _dot_f32(ds.astype(k_ref.dtype), k_ref[:]) * scale

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, window, block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _block_live(qi, ki, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        s = _dot_f32(q_ref[:], k_ref[:], trans_b=True) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, window)
        p = jnp.exp(s - lse_ref[:, :1])                      # [bq, bk] f32
        pv = p.astype(do_ref.dtype)
        dv_scr[:] += _dot_f32(pv, do_ref[:], trans_a=True)   # [bk, D]
        dp = _dot_f32(do_ref[:], v_ref[:], trans_b=True)     # [bq, bk]
        ds = (p * (dp - di_ref[:, :1])).astype(q_ref.dtype)
        dk_scr[:] += _dot_f32(ds, q_ref[:], trans_a=True) * scale

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, *, scale, causal, window, block_q,
              block_k, interpret):
    bh, t, d = q.shape
    nq, nk = t // block_q, t // block_k
    # delta_i = rowsum(dO * O): cheap elementwise+reduce, leave it to XLA,
    # broadcast across lanes for block loading like lse
    di = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    di = jnp.broadcast_to(di[:, :, None], (bh, t, LANES))

    qspec = pl.BlockSpec((None, block_q, d), lambda b, qi, ki: (b, qi, 0))
    kv_idx = _kv_index(causal, block_q, block_k, window)
    kspec = pl.BlockSpec((None, block_k, d), kv_idx)
    rowq = pl.BlockSpec((None, block_q, LANES), lambda b, qi, ki: (b, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          window=window, block_q=block_q,
                          block_k=block_k),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=qspec,
        out_shape=_sds((bh, t, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, di)

    # k-major grid: swap the roles of the two minor axes
    q_idx = _q_index(causal, block_q, block_k, window)
    qspec2 = pl.BlockSpec((None, block_q, d), q_idx)
    kspec2 = pl.BlockSpec((None, block_k, d), lambda b, ki, qi: (b, ki, 0))
    rowq2 = pl.BlockSpec((None, block_q, LANES), q_idx)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          window=window, block_q=block_q,
                          block_k=block_k),
        grid=(bh, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowq2, rowq2],
        out_specs=[kspec2, kspec2],
        out_shape=[_sds((bh, t, d), q.dtype, q),
                   _sds((bh, t, d), q.dtype, q)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, di)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op: [B, T, H, D] in, custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, window, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, scale, causal, window, block_q, block_k,
                      interpret)
    return o


def _flash_fwd(q, k, v, scale, causal, window, block_q, block_k,
               interpret):
    o, lse = _fwd_call(q, k, v, scale=scale, causal=causal, window=window,
                       block_q=block_q, block_k=block_k, interpret=interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, window, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd_call(q, k, v, o, lse, g, scale=scale,
                           causal=causal, window=window, block_q=block_q,
                           block_k=block_k, interpret=interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    window: Optional[int] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention on ``[B, T, H, D]`` tensors (layer layout).

    Requires T to be a multiple of the block sizes (see :func:`supports`);
    when blocks are not given the largest exact tiling up to the measured
    sweet spot (512/1024) is chosen.  D is zero-padded to a 128-lane
    multiple internally (exact, including gradients).  Softmax scale is
    1/sqrt(true D).

    ``window`` (requires ``causal``) bands the attention to the last
    ``window`` positions per query; blocks outside the band skip both
    compute and their HBM fetches (two-sided index clamping).
    """
    from deeplearning4j_tpu.nn.layers.attention import check_window

    b, t, h, d = q.shape
    check_window(causal, window)
    picked = pick_blocks(t, block_q, block_k)
    if picked is None:
        raise ValueError(
            f"flash_attention needs T % block == 0 (T={t}, block_q={block_q},"
            f" block_k={block_k}); use dot_product_attention instead")
    block_q, block_k = picked
    if interpret is None:
        interpret = _interpret()
    scale = 1.0 / (d ** 0.5)  # softmax scale uses the TRUE head dim

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, x.shape[-1])

    dp = (-d) % LANES
    if dp:
        pad = ((0, 0), (0, 0), (0, 0), (0, dp))
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    o = _flash(to_bh(q), to_bh(k), to_bh(v), scale, causal, window,
               block_q, block_k, interpret)
    o = o.reshape(b, h, t, d + dp).transpose(0, 2, 1, 3)
    return o[..., :d] if dp else o


class FlashAttentionHelper:
    """Discovery-seam wrapper (≙ CudnnConvolutionHelper behind the
    ConvolutionHelper SPI): ``SelfAttentionLayer`` asks
    ``helpers.get_helper("attention")`` and uses this when the shape tiles.

    ``allow_interpret`` keeps the fused path OFF the non-TPU hot paths by
    default (the interpreter is for parity tests, not speed); tests flip it
    to exercise the routing end-to-end on the CPU tier.
    """

    def __init__(self, allow_interpret: bool = False):
        self.allow_interpret = allow_interpret

    def supports(self, t: int, d: int, *, under_shard_map: bool = False) -> bool:
        """Single routing policy for every call site (the attention layer
        and the sequence-parallel paths).  ``under_shard_map=True`` adds
        the constraint that only the compiled path qualifies: the Pallas
        HLO interpreter cannot execute under shard_map's varying-axes
        checks."""
        on_tpu = jax.default_backend() == "tpu"
        if not (on_tpu or self.allow_interpret):
            return False
        if under_shard_map and not on_tpu:
            return False
        return supports(t, d)

    def attend(self, q, k, v, *, causal: bool = False,
               window: Optional[int] = None) -> jax.Array:
        return flash_attention(q, k, v, causal=causal, window=window)
