"""Fused paged decode attention (the ``gather_pages`` seam, fused).

ROADMAP item 1's decode half: the continuous-batching engine's hot loop
used to materialize every row's logical KV view from the page pool
(``gather_pages`` -> ``paged_attention`` in ``nn/layers/attention.py``)
— a ``[B, MAXP*page_size, Hkv, D]`` round trip through HBM per layer
per decode step, just to immediately reduce it through a softmax.  This
module computes the same per-row causal attention DIRECTLY from the
flattened page pool + int32 block tables, streaming pages block-by-block
with the online-softmax recurrence (running row-max ``m``, normaliser
``l`` — the flash-attention scheme, see ``helpers/flash_attention.py``),
so the gathered view is never built.

Two implementations behind one public op:

- ``impl="pallas"`` (default on TPU): a Pallas kernel on the grid
  ``(B, Hkv, MAXP)`` whose sequential page axis carries the softmax
  scratch.  The block table and per-row positions ride scalar prefetch
  (``PrefetchScalarGridSpec``), so each page's HBM->VMEM DMA is issued
  straight off ``block[b, p]`` — the kernel IS the gather.  Pages that
  lie wholly above every live position of a row batch are skipped:
  their compute is predicated off and their DMA index clamps to the
  last live page (the Pallas pipeline elides copies whose index did
  not change), so a 3-page row in a 32-page table pays for 3 pages.
- ``impl="lax"`` (default elsewhere): a compiled ``lax.fori_loop`` over
  pages with the same online-softmax accumulator, gathering only one
  ``[B, page_size, Hkv, D]`` page slab per iteration.  The loop bound
  is the live-page watermark ``max(q_positions)//page_size + 1`` — a
  traced value (no recompiles; decode is inference-only so the dynamic
  ``while_loop`` lowering needs no reverse pass), which is where the
  measured CPU decode win comes from: the legacy gather always pays
  all MAXP pages.

Semantics match the legacy pair exactly (the flag-selectable oracle):
GQA contracts the UNEXPANDED kv heads, masking is per-row
``q_positions >= key_position`` where a key's global position is its
logical slot index ``p*page_size + i`` — which also hides unwritten
pages and trash-page-0 padding entries (their logical slots sit past
the row's position).  See docs/serving.md "The fused decode kernel"
for the seam contract, including the plan to dequantize int8/fp8 pages
(ROADMAP item 3) inside this kernel.

Mode toggle (trace-time, like ``enable_helpers``):
``set_paged_attention_mode("gather")`` or env DL4J_TPU_PAGED_GATHER=1
routes ``SelfAttentionLayer._apply_paged`` back through the legacy
gather+softmax path — the bit-compatible oracle the parity tests and
the bench's before/after arm compare against.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.helpers import interpret_mode as _interpret

LANES = 128
NEG_INF = -1e30

# jax-version seams (same policy as helpers/flash_attention.py; the
# kernel-trust harness classifies these as reference-setup divergences)
_typeof = getattr(jax, "typeof", None)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

_VALID_MODES = ("fused", "gather")
_mode = ("gather" if os.environ.get("DL4J_TPU_PAGED_GATHER", "0") == "1"
         else "fused")


def set_paged_attention_mode(mode: str) -> None:
    """Select the paged decode path: ``"fused"`` (default — this module)
    or ``"gather"`` (the legacy gather+softmax oracle).  NOTE: routing
    happens at TRACE time; already-compiled decode programs (a started
    GenerationEngine's warmed program set) keep whichever path they were
    traced with — toggle BEFORE building the engine."""
    if mode not in _VALID_MODES:
        raise ValueError(f"paged attention mode {mode!r} not in "
                         f"{_VALID_MODES}")
    global _mode
    _mode = mode


def paged_attention_mode() -> str:
    return _mode


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying ``like``'s varying-mesh-axes set (see
    flash_attention._sds; jax.typeof is post-0.4.x)."""
    vma = getattr(_typeof(like), "vma", None) if _typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _dot_f32(a, b, trans_b=False):
    cb = 1 if trans_b else 0
    return jax.lax.dot_general(
        a, b, (((1,), (cb,)), ((), ())),
        preferred_element_type=jnp.float32)


def _check_shapes(q, pk, pv, block, q_positions, page_size):
    b, t, hq, d = q.shape
    if pk.ndim != 3 or pk.shape != pv.shape:
        raise ValueError(
            f"paged pools must be flattened [P*page_size, Hkv, D]; got "
            f"pk {pk.shape}, pv {pv.shape}")
    hkv = pk.shape[1]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if pk.shape[0] % page_size:
        raise ValueError(
            f"pool rows {pk.shape[0]} not a multiple of page_size "
            f"{page_size}")
    if block.shape[0] != b or block.ndim != 2:
        raise ValueError(
            f"block table {block.shape} does not match batch {b}")
    if q_positions.shape != (b, t):
        raise ValueError(
            f"q_positions {q_positions.shape} must be [B, T] = {(b, t)}")
    return hkv, d


# ---------------------------------------------------------------------------
# lax fallback: fori_loop over live pages, online softmax
# ---------------------------------------------------------------------------

def _lax_paged(q, pk, pv, block, q_positions, page_size):
    """Compiled page-streaming fallback for non-TPU backends.  One
    ``[B, page_size, Hkv, D]`` slab in flight at a time; loop bound is
    the dynamic live-page watermark (traced -> while_loop -> zero
    steady-state recompiles)."""
    b, t, hq, d = q.shape
    hkv = pk.shape[1]
    g = hq // hkv
    maxp = block.shape[1]
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    scale = 1.0 / (d ** 0.5)
    offs = jnp.arange(page_size, dtype=block.dtype)
    # [B, T, Hkv, G, D] — contract the UNEXPANDED kv heads (GQA)
    qg = q.reshape(b, t, hkv, g, d).astype(acc_dt)
    m0 = jnp.full((b, hkv, g, t), NEG_INF, acc_dt)
    l0 = jnp.zeros((b, hkv, g, t), acc_dt)
    a0 = jnp.zeros((b, t, hkv, g, d), acc_dt)

    def body(p, carry):
        m, l, acc = carry
        slots = block[:, p][:, None] * page_size + offs[None]  # [B, ps]
        k = pk[slots].astype(acc_dt)                  # [B, ps, Hkv, D]
        v = pv[slots].astype(acc_dt)
        kpos = p * page_size + offs
        s = jnp.einsum("bthgd,bkhd->bhgtk", qg, k) * scale
        keep = (q_positions[:, None, None, :, None]
                >= kpos[None, None, None, None, :])
        s = jnp.where(keep, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        p_exp = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p_exp, axis=-1)
        acc_new = (acc * alpha.transpose(0, 3, 1, 2)[..., None]
                   + jnp.einsum("bhgtk,bkhd->bthgd", p_exp, v))
        return m_new, l_new, acc_new

    live = jnp.minimum(jnp.max(q_positions) // page_size + 1, maxp)
    m, l, acc = jax.lax.fori_loop(0, live, body, (m0, l0, a0))
    safe = jnp.where(l > 0, l, 1.0)                   # NaN-safe idle rows
    o = acc / safe.transpose(0, 3, 1, 2)[..., None]
    return o.reshape(b, t, hq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (B, Hkv, MAXP), scalar-prefetched block table
# ---------------------------------------------------------------------------

def _row_max_qpos(qp_ref, b, t):
    m = qp_ref[b, 0]
    for i in range(1, t):
        m = jnp.maximum(m, qp_ref[b, i])
    return m


def _decode_kernel(blk_ref, qp_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, page_size, t):
    b = pl.program_id(0)
    p = pl.program_id(2)
    npages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # pages wholly above every row position contribute nothing; their
    # DMA already clamped to the last live page (see _kv_index)
    run = p * page_size <= _row_max_qpos(qp_ref, b, t)

    @pl.when(run)
    def _step():
        s = _dot_f32(q_ref[:], k_ref[:], trans_b=True) * scale  # [GT, ps]
        kpos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        # q rows are laid out [G, T] flattened (t = row % T); per-row
        # global positions come off the prefetched scalars
        qpm = jnp.full(s.shape, qp_ref[b, 0], jnp.int32)
        if t > 1:
            rt = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) % t
            for i in range(1, t):
                qpm = jnp.where(rt == i, qp_ref[b, i], qpm)
        s = jnp.where(qpm >= kpos, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p_exp = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p_exp, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + _dot_f32(
            p_exp.astype(v_ref.dtype), v_ref[:])
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(p == npages - 1)
    def _finish():
        l = l_scr[:, :1]
        safe = jnp.where(l > 0, l, 1.0)               # idle / trash rows
        o_ref[:] = (acc_scr[:] / safe).astype(o_ref.dtype)


def _kv_index(page_size, t):
    """K/V page index straight off the scalar-prefetched block table;
    dead pages clamp to the last live one so their copies are elided."""
    def idx(b, h, p, blk_ref, qp_ref):
        hi = _row_max_qpos(qp_ref, b, t) // page_size
        return (blk_ref[b, jnp.minimum(p, hi)], h, 0)
    return idx


def _pallas_paged(q, pk, pv, block, q_positions, page_size, interpret):
    b, t, hq, d = q.shape
    hkv = pk.shape[1]
    g = hq // hkv
    gt = g * t
    maxp = block.shape[1]
    scale = 1.0 / (d ** 0.5)
    dp = (-d) % LANES
    if dp:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, dp)))
        pk = jnp.pad(pk, ((0, 0), (0, 0), (0, dp)))
        pv = jnp.pad(pv, ((0, 0), (0, 0), (0, dp)))
    dpad = d + dp
    # [B, Hkv, G*T, D]: one grid step owns one (batch row, kv head)
    qb = (q.reshape(b, t, hkv, g, dpad).transpose(0, 2, 3, 1, 4)
          .reshape(b, hkv, gt, dpad))
    block = block.astype(jnp.int32)
    qpos = q_positions.astype(jnp.int32)
    kern = functools.partial(_decode_kernel, scale=scale,
                             page_size=page_size, t=t)
    kv_idx = _kv_index(page_size, t)
    o = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, hkv, maxp),
            in_specs=[
                pl.BlockSpec((None, None, gt, dpad),
                             lambda bi, h, p, blk, qp: (bi, h, 0, 0)),
                pl.BlockSpec((page_size, None, dpad), kv_idx),
                pl.BlockSpec((page_size, None, dpad), kv_idx),
            ],
            out_specs=pl.BlockSpec(
                (None, None, gt, dpad),
                lambda bi, h, p, blk, qp: (bi, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((gt, LANES), jnp.float32),
                pltpu.VMEM((gt, LANES), jnp.float32),
                pltpu.VMEM((gt, dpad), jnp.float32),
            ],
        ),
        out_shape=_sds((b, hkv, gt, dpad), q.dtype, q),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(block, qpos, qb, pk, pv)
    o = (o.reshape(b, hkv, g, t, dpad).transpose(0, 3, 1, 2, 4)
         .reshape(b, t, hq, dpad))
    return o[..., :d] if dp else o


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

def paged_decode_attention(q: jax.Array, pk: jax.Array, pv: jax.Array,
                           block: jax.Array, q_positions: jax.Array, *,
                           page_size: int,
                           impl: Optional[str] = None,
                           interpret: Optional[bool] = None) -> jax.Array:
    """Per-row causal attention of ``q`` [B, T, Hq, D] directly over the
    flattened page pool ``pk``/``pv`` [P*page_size, Hkv, D] through the
    int32 block table ``block`` [B, MAXP] — never materializing the
    gathered [B, MAXP*page_size, Hkv, D] view.

    A key's global position is its logical slot index
    ``p * page_size + i``; masking is ``q_positions >= key position``
    per row, which (exactly as the legacy ``paged_attention`` documents)
    also hides unwritten pages and trash-page-0 padding entries.  GQA
    contracts the unexpanded kv heads.

    ``impl``: None picks ``"pallas"`` on TPU and ``"lax"`` elsewhere;
    ``"gather"`` routes through the legacy gather+softmax pair (the
    bit-compatible oracle).  ``interpret`` only applies to the Pallas
    path (defaults to the package policy: interpret off-TPU).
    """
    hkv, d = _check_shapes(q, pk, pv, block, q_positions, page_size)
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "lax"
    if impl == "gather":
        from deeplearning4j_tpu.nn.layers.attention import (
            gather_pages, paged_attention)

        gk = gather_pages(pk, block, page_size).astype(q.dtype)
        gv = gather_pages(pv, block, page_size).astype(q.dtype)
        return paged_attention(q, gk, gv, q_positions)
    if impl == "lax":
        return _lax_paged(q, pk, pv, block, q_positions, page_size)
    if impl != "pallas":
        raise ValueError(f"impl={impl!r} not one of pallas/lax/gather")
    if interpret is None:
        interpret = _interpret()
    return _pallas_paged(q, pk, pv, block, q_positions, page_size,
                         interpret)


class PagedAttentionHelper:
    """Discovery-seam wrapper for the paged decode path (≙ the cuDNN
    helper SPI, like FlashAttentionHelper): ``SelfAttentionLayer.
    _apply_paged`` asks ``helpers.get_helper("paged_attention")`` and
    falls back to the legacy gather+softmax pair when this returns
    unsupported.  Unlike the flash helper, the fused path is the
    DEFAULT on every backend — off TPU it routes to the compiled lax
    page-streaming fallback, not the Pallas interpreter, so CPU decode
    gets the live-page watermark win too."""

    name = "PagedAttentionHelper"

    def supports(self, q, page_size: int) -> bool:
        return paged_attention_mode() == "fused"

    def attend(self, q, pk, pv, block, q_positions, *,
               page_size: int) -> jax.Array:
        return paged_decode_attention(q, pk, pv, block, q_positions,
                                      page_size=page_size)
