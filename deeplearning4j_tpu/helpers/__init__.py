"""Accelerated-helper plugin layer (≙ deeplearning4j-cuda).

Reference: the cuDNN helper SPI — ``deeplearning4j-nn/.../convolution/
ConvolutionHelper.java:30-35`` (interface declared in core),
``CudnnConvolutionHelper.java:51`` etc. (implementation in the acceleration
module), discovered via ``Class.forName`` at layer construction
(``ConvolutionLayer.java:58-65``) and transparently intercepting
forward/backward.

TPU translation: XLA already lowers conv/matmul/BN optimally onto the MXU,
so the helper layer holds *Pallas* kernels only where a hand-fused VMEM pass
beats stock XLA fusion (LRN's cross-channel window walk, fused BN-inference
affine), plus the same discovery seam: layers ask ``get_helper(kind)`` and
fall back to the pure-jnp path when helpers are disabled or unavailable —
exactly how the reference degrades without cuDNN on the classpath.

Toggle: ``enable_helpers(False)`` or env DL4J_TPU_DISABLE_HELPERS=1.
Kernels run compiled on TPU and in interpret mode elsewhere, so the parity
gradient-check suite (``tests/test_helpers.py``, ≙ CuDNNGradientChecks)
exercises the same code path everywhere.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

_enabled = os.environ.get("DL4J_TPU_DISABLE_HELPERS", "0") != "1"
_registry: Dict[str, object] = {}


def interpret_mode() -> bool:
    """Pallas kernels compile on TPU and run ``interpret=True`` elsewhere
    (single policy for every kernel in this package)."""
    import jax

    return jax.default_backend() != "tpu"


def enable_helpers(on: bool = True) -> None:
    """Toggle helper discovery.  NOTE: discovery happens at TRACE time, so
    already-jitted programs (e.g. a model's cached train/output step) keep
    whichever path they were traced with — toggle BEFORE first use, or use a
    fresh model/jit cache when comparing helper vs built-in paths."""
    global _enabled
    _enabled = on


def helpers_enabled() -> bool:
    return _enabled


def register_helper(kind: str, helper: object) -> None:
    _registry[kind] = helper


def get_helper(kind: str) -> Optional[object]:
    """≙ the Class.forName discovery: None when disabled/absent, in which
    case the layer uses its built-in path."""
    if not _enabled:
        return None
    helper = _registry.get(kind)
    if helper is None:
        # lazy registration on first ask
        from deeplearning4j_tpu.helpers import pallas_ops

        pallas_ops.register_default_helpers()
        helper = _registry.get(kind)
    return helper
