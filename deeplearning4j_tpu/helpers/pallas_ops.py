"""Pallas TPU kernels behind the helper seam.

≙ the cuDNN kernel implementations (``CudnnLocalResponseNormalizationHelper``,
``CudnnBatchNormalizationHelper``) — re-derived as Pallas VMEM passes:

- LRN forward + backward: the cross-channel window sum is materialised once
  per block via lane-rolls inside VMEM (one HBM read/write per tensor),
  where the stock XLA lowering builds an n-tap reduce_window; backward
  reuses the same window structure via a custom VJP.
- Fused BN inference: (x - mean) * rsqrt(var+eps) * gamma + beta in a single
  elementwise pass with the per-channel affine computed in-kernel.

Everything is rank-normalised to [rows, channels] blocks; wrappers pad rows
to sublane (8) and channels to lane (128) multiples and slice back.  On
non-TPU backends kernels run with ``interpret=True`` so CI and the parity
gradient checks execute the identical code path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu import helpers as _helpers


# single interpret policy for every kernel in the package
_interpret = _helpers.interpret_mode


def _pad2(x, row_mult=8, lane_mult=128):
    M, C = x.shape
    Mp = (M + row_mult - 1) // row_mult * row_mult
    Cp = (C + lane_mult - 1) // lane_mult * lane_mult
    if Mp == M and Cp == C:
        return x, M, C
    return jnp.pad(x, ((0, Mp - M), (0, Cp - C))), M, C


# ---------------------------------------------------------------------------
# LRN: y = x * (k + alpha * window_sum(x^2))^(-beta)
# ---------------------------------------------------------------------------

def _window_sum(vals, half: int, C: int):
    """Σ over channel offsets in [-half, half] with edge zeroing; lane rolls
    stay in-register on the VPU."""
    Cp = vals.shape[1]
    acc = jnp.zeros_like(vals)
    col = jax.lax.broadcasted_iota(jnp.int32, vals.shape, 1)
    for w in range(-half, half + 1):
        # circular roll by (-w mod Cp) puts vals[j+w] at lane j (roll shift
        # must be non-negative); edge wrap-around is masked out below
        rolled = pltpu.roll(vals, (-w) % Cp, 1) if w % Cp != 0 else vals
        valid = (col + w >= 0) & (col + w < C)
        acc = acc + jnp.where(valid, rolled, 0.0)
    return acc


def _lrn_fwd_kernel(x_ref, y_ref, s_ref, *, k, n, alpha, beta, C):
    x = x_ref[:]
    s = k + alpha * _window_sum(x * x, n // 2, C)
    y_ref[:] = x * jnp.power(s, -beta)
    s_ref[:] = s


def _lrn_bwd_kernel(x_ref, s_ref, g_ref, dx_ref, *, n, alpha, beta, C):
    x, s, g = x_ref[:], s_ref[:], g_ref[:]
    # dx = g·s^{-β} − 2αβ·x·Σ_win(g·x·s^{-β-1})
    t = g * x * jnp.power(s, -beta - 1.0)
    dx_ref[:] = g * jnp.power(s, -beta) \
        - 2.0 * alpha * beta * x * _window_sum(t, n // 2, C)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn(x2d, k, n, alpha, beta):
    return _lrn_fwd(x2d, k, n, alpha, beta)[0]


def _lrn_fwd(x2d, k, n, alpha, beta):
    xp, M, C = _pad2(x2d)
    kern = functools.partial(_lrn_fwd_kernel, k=k, n=n, alpha=alpha,
                             beta=beta, C=C)
    y, s = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct(xp.shape, xp.dtype),
                   jax.ShapeDtypeStruct(xp.shape, xp.dtype)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        interpret=_interpret(),
    )(xp)
    return y[:M, :C], (x2d, s[:M, :C])


def _lrn_fwd_rule(x2d, k, n, alpha, beta):
    y, res = _lrn_fwd(x2d, k, n, alpha, beta)
    return y, res


def _lrn_bwd_rule(k, n, alpha, beta, res, g):
    x2d, s = res
    xp, M, C = _pad2(x2d)
    # pad lanes may compute inf/nan (0^-β etc.) — they are window-masked out
    # of every valid lane and sliced off below, so zero padding is safe
    sp, _, _ = _pad2(s)
    gp, _, _ = _pad2(g)
    kern = functools.partial(_lrn_bwd_kernel, n=n, alpha=alpha, beta=beta, C=C)
    dx = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(xp.shape, xp.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(xp, sp, gp)
    return (dx[:M, :C],)


lrn.defvjp(_lrn_fwd_rule, _lrn_bwd_rule)


# ---------------------------------------------------------------------------
# fused BN inference: y = (x - mean) * rsqrt(var + eps) * gamma + beta
# ---------------------------------------------------------------------------

def _bn_inf_kernel(x_ref, mean_ref, var_ref, gamma_ref, beta_ref, y_ref, *, eps):
    scale = gamma_ref[:] * jax.lax.rsqrt(var_ref[:] + eps)
    y_ref[:] = x_ref[:] * scale + (beta_ref[:] - mean_ref[:] * scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def bn_inference(x2d, mean, var, gamma, beta, eps):
    """Single fused elementwise pass (helper fast path for serving).
    Custom VJP: the affine backward is analytic, no need to differentiate
    through the pallas_call."""
    return _bn_inference_impl(x2d, mean, var, gamma, beta, eps)


def _bn_inference_fwd(x2d, mean, var, gamma, beta, eps):
    y = _bn_inference_impl(x2d, mean, var, gamma, beta, eps)
    return y, (x2d, mean, var, gamma)


def _bn_inference_bwd(eps, res, g):
    x2d, mean, var, gamma = res
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x2d - mean) * inv
    dx = g * (gamma * inv)
    dgamma = (g * xhat).sum(0)
    dbeta = g.sum(0)
    dmean = -(g.sum(0)) * gamma * inv
    dvar = (g * (x2d - mean)).sum(0) * gamma * (-0.5) * inv ** 3
    return dx, dmean, dvar, dgamma, dbeta


bn_inference.defvjp(_bn_inference_fwd, _bn_inference_bwd)


def _bn_inference_impl(x2d, mean, var, gamma, beta, eps):
    xp, M, C = _pad2(x2d)
    Cp = xp.shape[1]

    def pad_c(v, fill=0.0):
        return jnp.pad(v.reshape(1, -1), ((0, 0), (0, Cp - C)),
                       constant_values=fill)

    kern = functools.partial(_bn_inf_kernel, eps=eps)
    y = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(xp.shape, xp.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(xp, pad_c(mean), pad_c(var, 1.0), pad_c(gamma), pad_c(beta))
    return y[:M, :C]


# ---------------------------------------------------------------------------
# fused BN training: one VMEM pass computing batch mean/var + normalize,
# one fused backward pass (≙ cudnnBatchNormalizationForwardTraining/Backward)
# ---------------------------------------------------------------------------

def _bn_train_kernel(x_ref, gamma_ref, beta_ref, y_ref, xhat_ref, stats_ref,
                     *, eps, M):
    x = x_ref[:]
    row = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    valid = row < M            # zero-padded rows must not bias the moments
    xm = jnp.where(valid, x, 0.0)
    mean = jnp.sum(xm, 0) / M
    diff = jnp.where(valid, x - mean, 0.0)
    var = jnp.sum(diff * diff, 0) / M
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * inv
    y_ref[:] = xhat * gamma_ref[:] + beta_ref[:]
    xhat_ref[:] = xhat
    stats_ref[:] = jnp.stack([mean, var, inv])[:, None, :].reshape(3, -1)


def _bn_train_bwd_kernel(xhat_ref, g_ref, gammainv_ref, dx_ref, dgb_ref,
                         *, M):
    """dx = (gamma*inv/M) * (M*g - Σg - xhat*Σ(g*xhat)); padded rows carry
    g == 0 so the channel sums are already valid-row sums."""
    xhat, g = xhat_ref[:], g_ref[:]
    sum_g = jnp.sum(g, 0)
    sum_gx = jnp.sum(g * xhat, 0)
    dx_ref[:] = (gammainv_ref[:] / M) * (M * g - sum_g - xhat * sum_gx)
    dgb_ref[:] = jnp.stack([sum_gx, sum_g])[:, None, :].reshape(2, -1)


def bn_training(x2d, gamma, beta, eps):
    """Fused training-mode BN: returns (y, batch_mean, batch_var) from one
    VMEM pass; differentiable via a fused backward kernel (custom VJP).
    Gradients flow to (x2d, gamma, beta); the returned moments feed the
    running-stats update, which the reference does not differentiate."""
    return _bn_training_vjp(x2d, gamma, beta, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_training_vjp(x2d, gamma, beta, eps):
    y, _, mean, var, _ = _bn_training_fwd_impl(x2d, gamma, beta, eps)
    return y, mean, var


def _bn_training_fwd_impl(x2d, gamma, beta, eps):
    xp, M, C = _pad2(x2d)
    Cp = xp.shape[1]

    def pad_c(v):
        return jnp.pad(v.reshape(1, -1), ((0, 0), (0, Cp - C)))

    kern = functools.partial(_bn_train_kernel, eps=eps, M=M)
    y, xhat, stats = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct(xp.shape, xp.dtype),
                   jax.ShapeDtypeStruct(xp.shape, xp.dtype),
                   jax.ShapeDtypeStruct((3, Cp), xp.dtype)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),) * 3,
        interpret=_interpret(),
    )(xp, pad_c(gamma), pad_c(beta))
    mean, var, inv = stats[0, :C], stats[1, :C], stats[2, :C]
    return y[:M, :C], xhat, mean, var, inv


def _bn_training_fwd_rule(x2d, gamma, beta, eps):
    y, xhat, mean, var, inv = _bn_training_fwd_impl(x2d, gamma, beta, eps)
    return (y, mean, var), (xhat, inv, gamma, x2d.shape)


def _bn_training_bwd_rule(eps, res, cts):
    g = cts[0]  # moments feed running stats only: their cotangents are zero
    xhat_p, inv, gamma, (M, C) = res
    gp, _, _ = _pad2(g)
    Cp = xhat_p.shape[1]
    gammainv = jnp.pad((gamma * inv).reshape(1, -1), ((0, 0), (0, Cp - C)))
    kern = functools.partial(_bn_train_bwd_kernel, M=M)
    dx, dgb = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct(xhat_p.shape, xhat_p.dtype),
                   jax.ShapeDtypeStruct((2, Cp), xhat_p.dtype)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),) * 2,
        interpret=_interpret(),
    )(xhat_p, gp, gammainv)
    return dx[:M, :C], dgb[0, :C], dgb[1, :C]


_bn_training_vjp.defvjp(_bn_training_fwd_rule, _bn_training_bwd_rule)


# ---------------------------------------------------------------------------
# helper objects + registration
# ---------------------------------------------------------------------------

# The kernels are single-block whole-array VMEM passes (no grid), so they
# only apply below a VMEM budget: ~16 MiB/core shared by ~3 live f32 buffers.
# Above it the layer's stock XLA path runs instead (which tiles fine).
_VMEM_BUDGET_ELEMS = 1 << 20   # 4 MiB per f32 buffer


def _fits_vmem(x) -> bool:
    rows = int(np.prod(x.shape[:-1]))
    cols = x.shape[-1]
    padded = ((rows + 7) // 8 * 8) * ((cols + 127) // 128 * 128)
    return padded <= _VMEM_BUDGET_ELEMS


class PallasLRNHelper:
    """≙ ``CudnnLocalResponseNormalizationHelper``."""

    name = "PallasLRNHelper"

    def supports(self, x) -> bool:
        return _fits_vmem(x)

    def apply(self, x, k, n, alpha, beta):
        shape = x.shape
        x2d = x.reshape(-1, shape[-1])
        return lrn(x2d, float(k), int(n), float(alpha), float(beta)).reshape(shape)


class PallasBatchNormHelper:
    """≙ ``CudnnBatchNormalizationHelper`` (inference + training paths)."""

    name = "PallasBatchNormHelper"

    def supports(self, x) -> bool:
        return _fits_vmem(x)

    def apply_inference(self, x, mean, var, gamma, beta, eps):
        shape = x.shape
        x2d = x.reshape(-1, shape[-1])
        return bn_inference(x2d, mean, var, gamma, beta, float(eps)).reshape(shape)

    def apply_training(self, x, gamma, beta, eps):
        """Fused forward-training pass; returns (y, batch_mean, batch_var)
        (≙ cudnnBatchNormalizationForwardTraining's saved moments)."""
        shape = x.shape
        x2d = x.reshape(-1, shape[-1])
        y, mean, var = bn_training(x2d, gamma, beta, float(eps))
        return y.reshape(shape), mean, var


def register_default_helpers() -> None:
    if "lrn" not in _helpers._registry:
        _helpers.register_helper("lrn", PallasLRNHelper())
    if "batch_norm" not in _helpers._registry:
        _helpers.register_helper("batch_norm", PallasBatchNormHelper())
    if "attention" not in _helpers._registry:
        from deeplearning4j_tpu.helpers.flash_attention import FlashAttentionHelper

        _helpers.register_helper("attention", FlashAttentionHelper())
    if "paged_attention" not in _helpers._registry:
        from deeplearning4j_tpu.helpers.paged_attention import PagedAttentionHelper

        _helpers.register_helper("paged_attention", PagedAttentionHelper())
    if "epilogue" not in _helpers._registry:
        from deeplearning4j_tpu.helpers.fused_epilogue import FusedEpilogueHelper

        _helpers.register_helper("epilogue", FusedEpilogueHelper())
