"""Fused dropout + residual + norm epilogue (ROADMAP item 1, train side).

The transformer train step's other memory-bound seam: between the
attention/MLP matmuls sit chains of cheap elementwise passes — residual
add, LayerNorm's two reductions + affine, the next sublayer's input
dropout — each a full HBM round trip when left to generic lowering.
This kernel computes

    out = dropout(LayerNorm_affine(res + h))

in ONE VMEM pass (add, mean/var reductions, affine, mask-scale), with
``res=None`` giving the prologue form ``dropout(LayerNorm(x))`` — the
shape that actually occurs INSIDE this repo's pre-norm ResidualBlock
(LayerNorm leads the block; the residual add closes it; the full
res+h form is the cross-block fusion the kerneldiff grid and the tests
exercise).  ``ResidualBlock.apply`` routes its leading LayerNorm + the
second sublayer's input dropout through the prologue when the helper
qualifies (see ``_fused_prologue`` there).

Dropout discipline: the bernoulli keep-mask is drawn OUTSIDE the kernel
with exactly ``Layer.maybe_dropout``'s ops (``jax.random.bernoulli(rng,
1-rate, shape)`` + inverted scaling), so the fused path's mask is
bit-identical to the unfused path's for the same rng key; the kernel
only applies ``mask * y / keep``.  Tests pass an explicit ``mask`` for
exact referencing.

Backward: a custom VJP saving (h, res, gamma, mask); the backward pass
is plain jnp from the recomputed row moments (the standard LayerNorm
adjoint), so the fused forward is fully differentiable — including
under ``jax.checkpoint`` in remat blocks.

Same helper discipline as the rest of the package: registered as kind
``"epilogue"``; ``allow_interpret=False`` keeps the Pallas path off
non-TPU hot paths (the interpreter is for parity tests, not speed) —
off-TPU the layer's stock jnp path runs, which IS the reference.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.helpers import interpret_mode as _interpret

_VMEM_BUDGET_ELEMS = 1 << 20   # single-block pass, same cap as pallas_ops


def _pad2(x, row_mult=8, lane_mult=128):
    M, C = x.shape
    Mp = (M + row_mult - 1) // row_mult * row_mult
    Cp = (C + lane_mult - 1) // lane_mult * lane_mult
    if Mp == M and Cp == C:
        return x, M, C
    return jnp.pad(x, ((0, Mp - M), (0, Cp - C))), M, C


def _drn_kernel(*refs, eps, keep, C, has_res, has_mask):
    """refs: h [, res], gamma, beta [, mask], out.  One VMEM pass:
    x = h (+ res); row moments over the TRUE C lanes; affine; inverted
    dropout scaling by the precomputed keep-mask."""
    i = 0
    h_ref = refs[i]; i += 1
    res_ref = None
    if has_res:
        res_ref = refs[i]; i += 1
    g_ref = refs[i]; b_ref = refs[i + 1]; i += 2
    m_ref = None
    if has_mask:
        m_ref = refs[i]; i += 1
    o_ref = refs[i]

    x = h_ref[:].astype(jnp.float32)
    if has_res:
        x = x + res_ref[:].astype(jnp.float32)
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = col < C                      # padded lanes must not bias moments
    xm = jnp.where(valid, x, 0.0)
    mu = jnp.sum(xm, axis=1, keepdims=True) / C
    diff = jnp.where(valid, x - mu, 0.0)
    var = jnp.sum(diff * diff, axis=1, keepdims=True) / C
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    y = y * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    if has_mask:
        y = y * m_ref[:].astype(jnp.float32) * (1.0 / keep)
    o_ref[:] = y.astype(o_ref.dtype)


def _drn_call(h2d, res2d, gamma, beta, maskf, eps, keep, has_res,
              has_mask):
    hp, M, C = _pad2(h2d)
    Cp = hp.shape[1]

    def pad_c(v):
        return jnp.pad(v.reshape(1, -1).astype(h2d.dtype),
                       ((0, 0), (0, Cp - C)))

    ops = [hp]
    if has_res:
        ops.append(_pad2(res2d)[0])
    ops += [pad_c(gamma), pad_c(beta)]
    if has_mask:
        ops.append(_pad2(maskf)[0])
    kern = functools.partial(_drn_kernel, eps=eps, keep=keep, C=C,
                             has_res=has_res, has_mask=has_mask)
    y = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(hp.shape, hp.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(ops),
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(*ops)
    return y[:M, :C]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _drn(h2d, res2d, gamma, beta, maskf, eps, keep, has_res, has_mask):
    return _drn_call(h2d, res2d, gamma, beta, maskf, eps, keep, has_res,
                     has_mask)


def _drn_fwd(h2d, res2d, gamma, beta, maskf, eps, keep, has_res,
             has_mask):
    y = _drn_call(h2d, res2d, gamma, beta, maskf, eps, keep, has_res,
                  has_mask)
    return y, (h2d, res2d, gamma, maskf)


def _drn_bwd(eps, keep, has_res, has_mask, res, g):
    """Standard LayerNorm adjoint from recomputed row moments, with the
    dropout mask-scale folded into the incoming cotangent."""
    h2d, res2d, gamma, maskf = res
    x = h2d.astype(jnp.float32)
    if has_res:
        x = x + res2d.astype(jnp.float32)
    C = x.shape[1]
    mu = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.var(x, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mu) * rstd
    g32 = g.astype(jnp.float32)
    if has_mask:
        g32 = g32 * maskf.astype(jnp.float32) * (1.0 / keep)
    dgamma = jnp.sum(g32 * xhat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(g32, axis=0).astype(gamma.dtype)
    dxhat = g32 * gamma.astype(jnp.float32)
    dx = rstd * (dxhat
                 - jnp.mean(dxhat, axis=1, keepdims=True)
                 - xhat * jnp.mean(dxhat * xhat, axis=1, keepdims=True))
    dh = dx.astype(h2d.dtype)
    dres = dx.astype(res2d.dtype) if has_res else jnp.zeros_like(res2d)
    return dh, dres, dgamma, dbeta, jnp.zeros_like(maskf)


_drn.defvjp(_drn_fwd, _drn_bwd)


def dropout_residual_norm(h: jax.Array, res: Optional[jax.Array],
                          gamma: jax.Array, beta: jax.Array, *,
                          eps: float = 1e-5, rate: float = 0.0,
                          rng: Optional[jax.Array] = None,
                          train: bool = False,
                          mask: Optional[jax.Array] = None) -> jax.Array:
    """``dropout(LayerNorm_affine(res + h))`` on ``[..., C]`` tensors in
    one fused VMEM pass; ``res=None`` gives the prologue form
    ``dropout(LayerNorm(h))``.

    Dropout applies when ``mask`` is given explicitly, or when ``train``
    and ``rate > 0`` (mask drawn from ``rng`` exactly like
    ``Layer.maybe_dropout`` — bit-identical masks for the same key);
    otherwise the output is the plain fused norm.
    """
    shape = h.shape
    C = shape[-1]
    h2d = h.reshape(-1, C)
    has_res = res is not None
    res2d = (res.reshape(-1, C) if has_res
             else jnp.zeros((0, C), h2d.dtype))
    keep = 1.0 - rate
    if mask is None and train and rate > 0.0:
        if rng is None:
            raise ValueError(
                "dropout_residual_norm: rate > 0 at train time requires "
                "an rng key (or an explicit mask)")
        mask = jax.random.bernoulli(rng, keep, shape)
    has_mask = mask is not None
    maskf = (mask.reshape(-1, C).astype(h2d.dtype) if has_mask
             else jnp.zeros((0, C), h2d.dtype))
    out = _drn(h2d, res2d, gamma, beta, maskf, float(eps), float(keep),
               has_res, has_mask)
    return out.reshape(shape)


class FusedEpilogueHelper:
    """Discovery-seam wrapper (kind ``"epilogue"``).  ``allow_interpret``
    keeps the fused path OFF non-TPU hot paths by default, exactly like
    FlashAttentionHelper — the CPU tier's stock jnp LayerNorm+dropout IS
    the reference; tests flip it to exercise the routing end-to-end."""

    name = "FusedEpilogueHelper"

    def __init__(self, allow_interpret: bool = False):
        self.allow_interpret = allow_interpret

    def supports(self, x) -> bool:
        import numpy as np

        if not (jax.default_backend() == "tpu" or self.allow_interpret):
            return False
        if x.dtype not in (jnp.float32, jnp.bfloat16):
            return False   # f64 gradient checks stay on the exact path
        rows = int(np.prod(x.shape[:-1]))
        cols = x.shape[-1]
        padded = ((rows + 7) // 8 * 8) * ((cols + 127) // 128 * 128)
        return padded <= _VMEM_BUDGET_ELEMS

    def prologue(self, x, gamma, beta, *, eps, rate=0.0, rng=None,
                 train=False):
        return dropout_residual_norm(x, None, gamma, beta, eps=eps,
                                     rate=rate, rng=rng, train=train)

    def epilogue(self, h, resid, gamma, beta, *, eps, rate=0.0, rng=None,
                 train=False, mask=None):
        return dropout_residual_norm(h, resid, gamma, beta, eps=eps,
                                     rate=rate, rng=rng, train=train,
                                     mask=mask)
