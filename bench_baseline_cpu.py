"""Measure the reference-class CPU baselines bench.py compares against.

The reference stack (DL4J 0.4 on nd4j-native CPU BLAS) publishes no numbers
(BASELINE.md); torch-CPU implementations of the same three benchmark configs
stand in as the reference-class CPU measurement.  Run this script in the
image to (re)produce ``baseline_cpu.json`` — bench.py reads that file, so the
comparison constants are reproducible, not hand-waved:

    python bench_baseline_cpu.py          # writes baseline_cpu.json

Configs mirror BASELINE.json: LeNet-5 b128 MNIST-shape, ResNet-50 b8 224^2,
GravesLSTM-class char-LM (2x200 LSTM, vocab 77) b64 T50.
"""

import json
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F


def _time_steps(step, warmup, iters):
    for _ in range(warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(iters):
        step()
    return (time.perf_counter() - t0) / iters


def lenet_step_ms(batch=128, warmup=2, iters=10):
    model = nn.Sequential(
        nn.Conv2d(1, 20, 5), nn.MaxPool2d(2, 2),
        nn.Conv2d(20, 50, 5), nn.MaxPool2d(2, 2),
        nn.Flatten(), nn.Linear(50 * 4 * 4, 500), nn.ReLU(),
        nn.Linear(500, 10),
    )
    opt = torch.optim.SGD(model.parameters(), lr=0.01, momentum=0.9)
    x = torch.randn(batch, 1, 28, 28)
    y = torch.randint(0, 10, (batch,))

    def step():
        opt.zero_grad()
        F.cross_entropy(model(x), y).backward()
        opt.step()

    return _time_steps(step, warmup, iters) * 1e3


class _Bottleneck(nn.Module):
    def __init__(self, cin, mid, stride):
        super().__init__()
        cout = mid * 4
        self.c1 = nn.Conv2d(cin, mid, 1, stride, bias=False)
        self.b1 = nn.BatchNorm2d(mid)
        self.c2 = nn.Conv2d(mid, mid, 3, 1, 1, bias=False)
        self.b2 = nn.BatchNorm2d(mid)
        self.c3 = nn.Conv2d(mid, cout, 1, bias=False)
        self.b3 = nn.BatchNorm2d(cout)
        self.proj = None
        if stride != 1 or cin != cout:
            self.proj = nn.Sequential(nn.Conv2d(cin, cout, 1, stride, bias=False),
                                      nn.BatchNorm2d(cout))

    def forward(self, x):
        s = self.proj(x) if self.proj is not None else x
        h = F.relu(self.b1(self.c1(x)))
        h = F.relu(self.b2(self.c2(h)))
        return F.relu(self.b3(self.c3(h)) + s)


def _resnet50():
    layers = [nn.Conv2d(3, 64, 7, 2, 3, bias=False), nn.BatchNorm2d(64),
              nn.ReLU(), nn.MaxPool2d(3, 2, 1)]
    cin, mid = 64, 64
    for stage, n in enumerate((3, 4, 6, 3)):
        for i in range(n):
            layers.append(_Bottleneck(cin, mid, 2 if (stage > 0 and i == 0) else 1))
            cin = mid * 4
        mid *= 2
    layers += [nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(cin, 1000)]
    return nn.Sequential(*layers)


def resnet50_imgs_per_sec(batch=8, warmup=1, iters=3):
    model = _resnet50()
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    x = torch.randn(batch, 3, 224, 224)
    y = torch.randint(0, 1000, (batch,))

    def step():
        opt.zero_grad()
        F.cross_entropy(model(x), y).backward()
        opt.step()

    return batch / _time_steps(step, warmup, iters)


def lstm_chars_per_sec(batch=64, seq=50, vocab=77, hidden=200, warmup=1, iters=5):
    class CharLM(nn.Module):
        def __init__(self):
            super().__init__()
            self.rnn = nn.LSTM(vocab, hidden, num_layers=2, batch_first=True)
            self.out = nn.Linear(hidden, vocab)

        def forward(self, x):
            h, _ = self.rnn(x)
            return self.out(h)

    model = CharLM()
    opt = torch.optim.RMSprop(model.parameters(), lr=0.1)
    ids = torch.randint(0, vocab, (batch, seq))
    x = F.one_hot(ids, vocab).float()
    y = torch.roll(ids, -1, 1)

    def step():
        opt.zero_grad()
        F.cross_entropy(model(x).reshape(-1, vocab), y.reshape(-1)).backward()
        opt.step()

    return batch * seq / _time_steps(step, warmup, iters)


def main():
    torch.manual_seed(0)
    out = {
        "lenet_step_ms": round(lenet_step_ms(), 3),
        "resnet50_imgs_per_sec": round(resnet50_imgs_per_sec(), 3),
        "lstm_chars_per_sec": round(lstm_chars_per_sec(), 1),
        "meta": {
            "stack": f"torch-{torch.__version__} CPU",
            "threads": torch.get_num_threads(),
            "note": "reference-class CPU stand-in (DL4J publishes no numbers)",
        },
    }
    with open("baseline_cpu.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
